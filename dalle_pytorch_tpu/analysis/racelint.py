"""racelint — whole-program concurrency lint for the threaded serve tier.

The serve tier is a real multi-threaded fleet — gateway hedged sends,
WFQ scheduler, tenancy token buckets, transport heartbeats, the flight
recorder ring, the autoscaler — all sharing state under ad-hoc
``threading.Lock``s spread across ten-plus modules, and the invariants
that keep it deadlock- and race-free lived only in reviewers' heads.
jaxlint proved the model (AST rules tuned to THIS repo's idioms, gated
in CI); racelint is the concurrency half of that catalog, built on the
same shared core (``lintcore``): same finding schema, same
``# racelint: disable=RL00x — reason`` waiver convention, same
``--json``/``--select``/``--ignore`` CLI and exit codes.

What it computes (stdlib only, whole-program over every linted file):

* a per-class LOCK TABLE — ``self._x = threading.Lock()`` attrs, plus
  module-level locks — each identified as ``ClassName.attr`` so a lock
  means the same thing in every module that touches it;
* RECEIVER TYPES — locals from constructor calls and annotations, attr
  types from ``self.x = Engine(...)`` and cross-object assignments
  (``r.engine = engine``), candidate SETS where assignment sites
  disagree, so ``eng._lock.acquire(timeout=0.2)`` in replica.py
  resolves to ``Engine._lock`` without imports saying so;
* a CALL GRAPH over resolved receivers (``self.m()``, typed locals and
  attrs, imported module functions, unique-method fallback with a
  common-name blocklist; ambiguity resolves to silence, same
  philosophy as jaxlint's project mode);
* fixpoints over that graph: which locks a call EVENTUALLY acquires
  (for the lock-order graph through method boundaries) and whether it
  eventually blocks (for blocking-reached-under-lock), plus per
  private method the locks ALWAYS held at entry (intersection over
  resolved self-call sites — the ``_reject``-style helper that is only
  ever called under the queue lock is guarded, not a race).

The statically computed lock-order graph is exported via
``lock_order_edges()`` and validated at runtime: ``analysis/guards.py``
ships a debug lock wrapper that records real acquisition order under
the test suite and asserts it is a subset of this graph — the static
analysis is tested against reality, not trusted.

Rules prefer missing a finding over flagging working idioms — the gate
only stays on in CI if the merged tree lints clean. Every finding can
be silenced in place with

    # racelint: disable=RL001 — reason why this one is fine

on the offending line (or the line above); the reason is part of the
convention, not enforced syntax.

Usage:
    racelint [paths...] [--json] [--select RL001,..] [--ignore RL00x,..]
    python -m dalle_pytorch_tpu.analysis.racelint dalle_pytorch_tpu

Exit status: 0 clean, 1 findings, 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

from . import lintcore
from .lintcore import (DEFAULT_EXCLUDES, Finding, iter_py_files,
                       dotted as _dotted, last as _last,
                       mod_parts as _mod_parts)

# rule id -> (slug, one-line description). docs/STATIC_ANALYSIS.md holds
# the long-form rationale; keep the two in sync.
RULES: Dict[str, Tuple[str, str]] = {
    "RL001": ("lock-guard",
              "attribute written both under its inferred lock and "
              "without it — a data-race candidate"),
    "RL002": ("lock-order-cycle",
              "cycle in the acquires-while-holding graph (through "
              "method calls) — a potential deadlock; also reentrant "
              "acquire of a non-reentrant self lock"),
    "RL003": ("blocking-under-lock",
              "blocking call (transport send/recv, sleep, select, "
              "subprocess, unbounded get/join/wait, device sync) "
              "reached while a lock is held"),
    "RL004": ("condvar-misuse",
              "Condition.wait() outside a while-predicate loop, or "
              "wait/notify without holding the condition"),
    "RL005": ("thread-lifecycle",
              "non-daemon thread that is never joined — it outlives "
              "shutdown and wedges interpreter exit"),
    "RL006": ("wallclock-deadline",
              "time.time() in deadline/duration arithmetic — wall "
              "clock steps under NTP; use time.monotonic()"),
}
lintcore.register_rules(RULES)

# self.<attr>.<mutator>(...) counts as a write to <attr>
_MUTATORS = {
    "append", "add", "update", "pop", "extend", "remove", "discard",
    "clear", "insert", "setdefault", "popitem", "appendleft",
    "popleft", "rotate",
}

# methods too common for the unique-name call-resolution fallback —
# a `.get()` is a dict far more often than it is the one class in the
# tree that happens to define get()
_COMMON_METHODS = {
    "get", "put", "pop", "push", "append", "add", "update", "remove",
    "clear", "close", "start", "stop", "run", "join", "wait", "notify",
    "send", "recv", "read", "write", "flush", "acquire", "release",
    "submit", "step", "reset", "items", "keys", "values", "copy",
    "result", "cancel", "set", "emit", "render", "open", "fileno",
    "encode", "decode", "next", "count", "index", "sort", "name",
}

_THREADING_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Event": "event", "Semaphore": "event", "BoundedSemaphore": "event",
    "Barrier": "event", "Thread": "thread", "Timer": "thread",
    "local": "event",
}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}

_BLOCKING_SUBPROCESS = {"run", "call", "check_call", "check_output",
                        "Popen", "communicate"}
_BLOCKING_SOCKETISH = {"recv", "recv_into", "recvfrom", "accept",
                       "connect", "sendall", "send_frame", "recv_frame",
                       "read_frame", "write_frame"}
# zero-argument forms of these block without bound
_BLOCKING_ZERO_ARG = {"join", "wait", "get", "result"}


class _Held(NamedTuple):
    lockid: str       # "ClassName.attr" / "module.name" / "scope.local"
    via_self: bool    # acquired on literal `self` (same instance)
    timed: bool       # acquire carried a timeout / non-blocking flag
    kind: str         # lock | rlock | condition


class _ClassInfo:
    __slots__ = ("name", "mod", "node", "bases", "lock_attrs",
                 "excluded_attrs", "attr_types", "methods")

    def __init__(self, name: str, mod: "_Mod", node: ast.ClassDef):
        self.name = name
        self.mod = mod
        self.node = node
        self.bases: List[str] = [_last(b) for b in node.bases if _last(b)]
        self.lock_attrs: Dict[str, str] = {}     # attr -> kind
        self.excluded_attrs: Set[str] = set()    # events/queues/threads
        self.attr_types: Dict[str, Set[str]] = {}  # attr -> class names
        self.methods: Dict[str, ast.AST] = {}


class _Mod:
    __slots__ = ("path", "src", "tree", "parts", "import_from",
                 "module_alias", "threading_aliases", "time_aliases",
                 "queue_aliases", "classes", "functions", "module_locks")

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.parts = _mod_parts(path)
        self.import_from: Dict[str, Tuple[str, str]] = {}
        self.module_alias: Dict[str, str] = {}
        self.threading_aliases: Set[str] = set()
        self.time_aliases: Set[str] = set()
        self.queue_aliases: Set[str] = set()
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, ast.AST] = {}
        self.module_locks: Dict[str, str] = {}   # name -> kind
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "threading":
                        self.threading_aliases.add(alias)
                    elif a.name == "time":
                        self.time_aliases.add(alias)
                    elif a.name == "queue":
                        self.queue_aliases.add(alias)
                    self.module_alias[alias] = a.name if a.asname \
                        else alias
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    self.import_from[alias] = (mod, a.name)
                    self.module_alias[alias] = f"{mod}.{a.name}" \
                        if mod else a.name
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                info = _ClassInfo(stmt.name, self, stmt)
                self.classes[stmt.name] = info
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        info.methods[sub.name] = sub
                self._collect_class_attrs(info)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                kind = self.ctor_kind(stmt.value)
                if kind in ("lock", "rlock", "condition"):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_locks[tgt.id] = kind

    def ctor_kind(self, expr: ast.AST) -> Optional[str]:
        """'lock'/'rlock'/'condition'/'event'/'queue'/'thread' when
        ``expr`` constructs a threading/queue primitive, else None."""
        if not isinstance(expr, ast.Call):
            return None
        name = _last(expr.func)
        base = _dotted(expr.func).rsplit(".", 1)[0] \
            if isinstance(expr.func, ast.Attribute) else ""
        if base in self.threading_aliases and name in _THREADING_CTORS:
            return _THREADING_CTORS[name]
        if base in self.queue_aliases and name in _QUEUE_CTORS:
            return "queue"
        if not base and name in self.import_from:
            m, orig = self.import_from[name]
            if m == "threading" and orig in _THREADING_CTORS:
                return _THREADING_CTORS[orig]
            if m == "queue" and orig in _QUEUE_CTORS:
                return "queue"
        return None

    def _collect_class_attrs(self, info: _ClassInfo) -> None:
        for method in info.methods.values():
            for node in ast.walk(method):
                tgt = val = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    tgt, val = node.target, node.value
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                attr = tgt.attr
                kind = self.ctor_kind(val) if val is not None else None
                if kind in ("lock", "rlock", "condition"):
                    info.lock_attrs[attr] = kind
                    info.excluded_attrs.add(attr)
                elif kind in ("event", "queue", "thread"):
                    info.excluded_attrs.add(attr)
                if isinstance(node, ast.AnnAssign) \
                        and node.annotation is not None:
                    hint = _ann_class_names(node.annotation)
                    if hint:
                        info.attr_types.setdefault(attr,
                                                   set()).update(hint)


def _ann_class_names(ann: ast.AST) -> Set[str]:
    """Capitalized identifiers named in an annotation (including string
    annotations) — candidate project class names, filtered against the
    registry later."""
    out: Set[str] = set()
    for node in ast.walk(ann):
        if isinstance(node, (ast.Name, ast.Attribute)):
            n = _last(node)
            if n[:1].isupper():
                out.add(n)
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            for tokstr in node.value.replace("[", " ").replace(
                    "]", " ").replace(",", " ").replace(".", " ").split():
                if tokstr[:1].isupper():
                    out.add(tokstr)
    out -= {"Optional", "List", "Dict", "Set", "Tuple", "Sequence",
            "Iterable", "Iterator", "Callable", "Any", "Union",
            "Mapping", "FrozenSet", "Deque", "Type", "None", "True",
            "False"}
    return out


# fn key: (module parts, class name or None, function name)
_FnKey = Tuple[Tuple[str, ...], Optional[str], str]


class _FnFacts:
    __slots__ = ("key", "node", "cls", "mod", "acquire_events",
                 "call_events", "block_events", "write_events",
                 "is_private")

    def __init__(self, key: _FnKey, node: ast.AST, cls: Optional[_ClassInfo],
                 mod: _Mod):
        self.key = key
        self.node = node
        self.cls = cls
        self.mod = mod
        # (held_snapshot, new _Held, line, col)
        self.acquire_events: List[Tuple] = []
        # (callee_keys, receiver_is_self, held_snapshot, line, col, label)
        self.call_events: List[Tuple] = []
        # (desc, held_snapshot, line, col)
        self.block_events: List[Tuple] = []
        # (attr, frozenset(self-held lockids), line, col)
        self.write_events: List[Tuple] = []
        name = key[2].rsplit(".", 1)[-1]
        self.is_private = name.startswith("_") and not name.startswith("__")


class _Project:
    def __init__(self, mods: List[_Mod]):
        self.mods = mods
        self.classes_by_name: Dict[str, List[_ClassInfo]] = {}
        for m in mods:
            for c in m.classes.values():
                self.classes_by_name.setdefault(c.name, []).append(c)
        self.methods_by_name: Dict[str, List[_ClassInfo]] = {}
        for m in mods:
            for c in m.classes.values():
                for name in c.methods:
                    self.methods_by_name.setdefault(name, []).append(c)
        self.facts: Dict[_FnKey, _FnFacts] = {}

    def resolve_class(self, name: str) -> Optional[_ClassInfo]:
        cands = self.classes_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def mro(self, cls: _ClassInfo) -> List[_ClassInfo]:
        out, seen, work = [], set(), [cls]
        while work and len(out) < 12:
            c = work.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            out.append(c)
            for b in c.bases:
                bc = self.resolve_class(b)
                if bc is not None:
                    work.append(bc)
        return out

    def find_method(self, cls: _ClassInfo,
                    name: str) -> Optional[Tuple[_ClassInfo, ast.AST]]:
        for c in self.mro(cls):
            if name in c.methods:
                return c, c.methods[name]
        return None

    def find_lock_attr(self, cls: _ClassInfo,
                       attr: str) -> Optional[Tuple[_ClassInfo, str]]:
        for c in self.mro(cls):
            if attr in c.lock_attrs:
                return c, c.lock_attrs[attr]
        return None

    def attr_type_names(self, cls: _ClassInfo, attr: str) -> Set[str]:
        out: Set[str] = set()
        for c in self.mro(cls):
            out |= c.attr_types.get(attr, set())
        return out

    def excluded_attr(self, cls: _ClassInfo, attr: str) -> bool:
        return any(attr in c.excluded_attrs for c in self.mro(cls))

    def find_mod(self, modref: str,
                 importer: Optional[_Mod] = None) -> Optional[_Mod]:
        """Longest-suffix module resolution (jaxlint's project-mode
        convention): ambiguity resolves to None; a bare one-part name
        binds only a same-directory sibling of the importer."""
        parts = tuple(p for p in modref.split(".") if p)
        if not parts:
            return None
        best: List[_Mod] = []
        best_k = 0
        for m in self.mods:
            k = min(len(parts), len(m.parts))
            if k and parts[-k:] == m.parts[-k:]:
                if k == 1 and len(parts) == 1 and importer is not None \
                        and m.parts[:-1] != importer.parts[:-1]:
                    continue
                if k > best_k:
                    best, best_k = [m], k
                elif k == best_k:
                    best.append(m)
        return best[0] if len(best) == 1 else None


class _FnCtx:
    __slots__ = ("project", "mod", "cls", "node", "key", "local_types",
                 "local_locks")

    def __init__(self, project: _Project, mod: _Mod,
                 cls: Optional[_ClassInfo], node: ast.AST, key: _FnKey):
        self.project = project
        self.mod = mod
        self.cls = cls
        self.node = node
        self.key = key
        self.local_types: Dict[str, Set[str]] = {}
        self.local_locks: Dict[str, Tuple[str, str]] = {}
        self._collect_locals()

    def _known(self, names: Set[str]) -> Set[str]:
        return {n for n in names
                if self.project.resolve_class(n) is not None}

    def expr_types(self, expr: ast.AST) -> Set[str]:
        """Candidate project-class names for an expression's value."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.cls is not None:
                return {self.cls.name}
            return self.local_types.get(expr.id, set())
        if isinstance(expr, ast.Attribute):
            recv_types = self.expr_types(expr.value)
            out: Set[str] = set()
            for tname in recv_types:
                c = self.project.resolve_class(tname)
                if c is not None:
                    out |= self._known(
                        self.project.attr_type_names(c, expr.attr))
            return out
        if isinstance(expr, ast.Subscript):
            # container-of-T access types as T (List[Engine] etc.)
            return self.expr_types(expr.value)
        if isinstance(expr, ast.Call):
            name = _last(expr.func)
            if self.project.resolve_class(name) is not None:
                return {name}
            return set()
        if isinstance(expr, ast.IfExp):
            return self.expr_types(expr.body) | self.expr_types(expr.orelse)
        if isinstance(expr, ast.Await):
            return self.expr_types(expr.value)
        return set()

    def _bind(self, tgt: ast.AST, val: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            t = self.expr_types(val)
            if t:
                self.local_types.setdefault(tgt.id, set()).update(t)
            kind = self.mod.ctor_kind(val)
            if kind in ("lock", "rlock", "condition"):
                scope = self.key[1] or self.mod.parts[-1]
                self.local_locks[tgt.id] = (
                    f"{scope}.{self.key[2]}.{tgt.id}", kind)
        elif isinstance(tgt, (ast.Tuple, ast.List)) \
                and isinstance(val, (ast.Tuple, ast.List)) \
                and len(tgt.elts) == len(val.elts):
            for t, v in zip(tgt.elts, val.elts):
                self._bind(t, v)

    def _collect_locals(self) -> None:
        args = getattr(self.node, "args", None)
        if args is not None:
            for p in args.posonlyargs + args.args + args.kwonlyargs:
                if p.annotation is not None:
                    names = self._known(_ann_class_names(p.annotation))
                    if len(names) == 1:
                        self.local_types[p.arg] = names
        for node in _shallow_walk_body(self.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                self._bind(node.targets[0], node.value)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                names = self._known(_ann_class_names(node.annotation))
                if len(names) == 1:
                    self.local_types[node.target.id] = names
                if node.value is not None:
                    self._bind(node.target, node.value)

    def resolve_lock(self, expr: ast.AST) -> Optional[Tuple[str, bool, str]]:
        """(lockid, via_self, kind) when ``expr`` denotes a known lock."""
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                lid, kind = self.local_locks[expr.id]
                return lid, False, kind
            if expr.id in self.mod.module_locks:
                return (f"{self.mod.parts[-1]}.{expr.id}", False,
                        self.mod.module_locks[expr.id])
            if expr.id in self.mod.import_from:
                modref, orig = self.mod.import_from[expr.id]
                t = self.project.find_mod(modref, self.mod)
                if t is not None and orig in t.module_locks:
                    return (f"{t.parts[-1]}.{orig}", False,
                            t.module_locks[orig])
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self.cls is not None:
            hit = self.project.find_lock_attr(self.cls, attr)
            if hit is not None:
                defcls, kind = hit
                return f"{defcls.name}.{attr}", True, kind
            return None
        # typed receiver: unique lock-owning candidate wins
        hits = []
        for tname in self.expr_types(expr.value):
            c = self.project.resolve_class(tname)
            if c is not None:
                hit = self.project.find_lock_attr(c, attr)
                if hit is not None:
                    hits.append(hit)
        ids = {(dc.name, kind) for dc, kind in hits}
        if len(ids) == 1:
            (defname, kind), = ids
            return f"{defname}.{attr}", False, kind
        # module-qualified lock: native._lock style
        modref = self.mod.module_alias.get(_dotted(expr.value), "")
        if modref:
            t = self.project.find_mod(modref, self.mod)
            if t is not None and attr in t.module_locks:
                return (f"{t.parts[-1]}.{attr}", False,
                        t.module_locks[attr])
        return None

    def resolve_call(self, call: ast.Call) -> Tuple[List[_FnKey], bool]:
        """(callee fn keys, receiver-is-literal-self)."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in self.mod.functions:
                return [(self.mod.parts, None, name)], False
            if name in self.mod.import_from:
                modref, orig = self.mod.import_from[name]
                t = self.project.find_mod(modref, self.mod)
                if t is not None and orig in t.functions:
                    return [(t.parts, None, orig)], False
            return [], False
        if not isinstance(func, ast.Attribute):
            return [], False
        mname = func.attr
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id == "self" \
                and self.cls is not None:
            hit = self.project.find_method(self.cls, mname)
            if hit is not None:
                defcls, _ = hit
                return [(defcls.mod.parts, defcls.name, mname)], True
            return [], False
        keys: List[_FnKey] = []
        for tname in self.expr_types(recv):
            c = self.project.resolve_class(tname)
            if c is not None:
                hit = self.project.find_method(c, mname)
                if hit is not None:
                    defcls, _ = hit
                    keys.append((defcls.mod.parts, defcls.name, mname))
        if keys:
            return sorted(set(keys)), False
        modref = self.mod.module_alias.get(_dotted(recv), "")
        if modref:
            t = self.project.find_mod(modref, self.mod)
            if t is not None and mname in t.functions:
                return [(t.parts, None, mname)], False
        # unique-method fallback: exactly one class in the whole linted
        # set defines this (non-common) method name
        if mname not in _COMMON_METHODS:
            owners = self.project.methods_by_name.get(mname, [])
            if len(owners) == 1:
                c = owners[0]
                return [(c.mod.parts, c.name, mname)], False
        return [], False


def _shallow_walk_body(fn: ast.AST):
    """Walk a function's body without descending into nested defs,
    lambdas, or class bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _inorder(node: ast.AST):
    """Source-order expression walk within one statement, not crossing
    nested function/class/lambda scopes."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from _inorder(child)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _Analyzer:
    """Lexical pass over one function: tracks the set of locks held at
    every point, records acquire/call/block/write events into the
    function's facts, and emits the purely-lexical findings (RL004,
    RL006, RL002's reentrancy half, RL005's raw thread ctors)."""

    def __init__(self, ctx: _FnCtx, facts: _FnFacts,
                 findings: List[Finding],
                 thread_ctors: List[Tuple]):
        self.ctx = ctx
        self.facts = facts
        self.findings = findings
        self.thread_ctors = thread_ctors
        self.path = ctx.mod.path

    # -- statement walker ---------------------------------------------------
    def walk(self) -> None:
        self._walk_body(list(getattr(self.facts.node, "body", [])),
                        [], 0)

    def _walk_body(self, stmts: Sequence[ast.stmt], held: List[_Held],
                   in_while: int) -> None:
        held = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new: List[_Held] = []
                for item in stmt.items:
                    r = self.ctx.resolve_lock(item.context_expr)
                    if r is not None:
                        lid, via_self, kind = r
                        h = _Held(lid, via_self, False, kind)
                        self._on_acquire(h, held + new,
                                         item.context_expr)
                        new.append(h)
                    else:
                        self._scan_expr(item.context_expr, held + new,
                                        in_while)
                self._walk_body(stmt.body, held + new, in_while)
            elif isinstance(stmt, ast.If):
                self._scan_expr(stmt.test, held, in_while)
                self._walk_body(stmt.body, held, in_while)
                self._walk_body(stmt.orelse, held, in_while)
            elif isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, held, in_while + 1)
                self._walk_body(stmt.body, held, in_while + 1)
                self._walk_body(stmt.orelse, held, in_while)
            elif isinstance(stmt, ast.For):
                self._scan_expr(stmt.iter, held, in_while)
                self._walk_body(stmt.body, held, in_while)
                self._walk_body(stmt.orelse, held, in_while)
            elif isinstance(stmt, ast.Try):
                self._walk_body(stmt.body, held, in_while)
                for h in stmt.handlers:
                    self._walk_body(h.body, held, in_while)
                self._walk_body(stmt.orelse, held, in_while)
                self._walk_body(stmt.finalbody, held, in_while)
            else:
                self._scan_stmt(stmt, held, in_while)

    # -- events -------------------------------------------------------------
    def _on_acquire(self, new: _Held, held: List[_Held],
                    site: ast.AST) -> None:
        self.facts.acquire_events.append(
            (tuple(held), new, site.lineno, site.col_offset))
        # reentrant self-acquire of a non-reentrant Lock is a definite
        # single-thread deadlock (with self._lock: ... with self._lock:)
        if new.kind == "lock" and not new.timed:
            for h in held:
                if h.lockid == new.lockid and h.via_self and new.via_self:
                    self.findings.append(Finding(
                        "RL002", self.path, site.lineno,
                        site.col_offset,
                        f"reentrant acquire of non-reentrant lock "
                        f"{new.lockid} already held by this thread — "
                        f"deadlock (use RLock or hoist the outer "
                        f"acquire)"))
                    break

    def _scan_stmt(self, stmt: ast.stmt, held: List[_Held],
                   in_while: int) -> None:
        self._record_writes(stmt, held)
        self._scan_expr(stmt, held, in_while)

    def _record_writes(self, stmt: ast.stmt, held: List[_Held]) -> None:
        if self.ctx.cls is None \
                or self.facts.key[2].split(".")[0] == "__init__":
            return
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        attrs: List[Tuple[str, ast.AST]] = []
        for tgt in targets:
            els = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for el in els:
                a = _self_attr(el)
                if a is not None and isinstance(stmt, (ast.Assign,
                                                       ast.AugAssign,
                                                       ast.AnnAssign)):
                    attrs.append((a, el))
                elif isinstance(el, ast.Subscript):
                    a = _self_attr(el.value)
                    if a is not None:
                        attrs.append((a, el))
        # mutator calls: self.X.append(...)
        for node in _inorder(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                a = _self_attr(node.func.value)
                if a is not None:
                    attrs.append((a, node))
        self_locks = frozenset(h.lockid for h in held if h.via_self)
        for attr, node in attrs:
            self.facts.write_events.append(
                (attr, self_locks, node.lineno, node.col_offset))

    def _scan_expr(self, root: ast.AST, held: List[_Held],
                   in_while: int) -> None:
        nodes = [root] if isinstance(root, ast.expr) else []
        nodes += list(_inorder(root))
        for node in nodes:
            if not isinstance(node, ast.Call):
                if isinstance(node, ast.expr):
                    self._check_wallclock(node)
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                recv_lock = self.ctx.resolve_lock(func.value)
                if func.attr == "acquire" and recv_lock is not None:
                    lid, via_self, kind = recv_lock
                    timed = any(kw.arg == "timeout"
                                for kw in node.keywords) \
                        or len(node.args) > 1 \
                        or (len(node.args) == 1
                            and not (isinstance(node.args[0], ast.Constant)
                                     and node.args[0].value is True))
                    h = _Held(lid, via_self, timed, kind)
                    self._on_acquire(h, held, node)
                    held.append(h)
                    continue
                if func.attr == "release" and recv_lock is not None:
                    lid = recv_lock[0]
                    for i in range(len(held) - 1, -1, -1):
                        if held[i].lockid == lid:
                            del held[i]
                            break
                    continue
                if recv_lock is not None and recv_lock[2] == "condition" \
                        and func.attr in ("wait", "wait_for", "notify",
                                          "notify_all"):
                    self._check_condvar(node, func.attr, recv_lock,
                                        held, in_while)
                    continue
            if self.ctx.mod.ctor_kind(node) == "thread":
                self.thread_ctors.append((self.ctx.mod, node))
                continue
            desc = self._blocking_desc(node)
            if desc is not None:
                self.facts.block_events.append(
                    (desc, tuple(held), node.lineno, node.col_offset))
                continue
            keys, via_self = self.ctx.resolve_call(node)
            if keys:
                self.facts.call_events.append(
                    (keys, via_self, tuple(held), node.lineno,
                     node.col_offset, _dotted(node.func) or "<call>"))

    # -- rule helpers -------------------------------------------------------
    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        func = call.func
        name = _last(func)
        base = _dotted(func).rsplit(".", 1)[0] \
            if isinstance(func, ast.Attribute) else ""
        mod = self.ctx.mod
        if base in mod.time_aliases and name == "sleep":
            return "time.sleep()"
        if mod.module_alias.get(base) == "select" \
                and name in ("select", "poll", "epoll"):
            return f"select.{name}()"
        if mod.module_alias.get(base) == "subprocess" \
                and name in _BLOCKING_SUBPROCESS:
            return f"subprocess.{name}()"
        if isinstance(func, ast.Name) and name in mod.import_from:
            m, orig = mod.import_from[name]
            if m == "subprocess" and orig in _BLOCKING_SUBPROCESS:
                return f"subprocess.{orig}()"
        if name == "device_get":
            return "jax.device_get() (host-device sync)"
        if name == "block_until_ready":
            return ".block_until_ready() (host-device sync)"
        if isinstance(func, ast.Attribute):
            if name in _BLOCKING_SOCKETISH:
                return f".{name}() (transport/socket I/O)"
            if name in _BLOCKING_ZERO_ARG and not call.args \
                    and not call.keywords:
                return f".{name}() with no timeout"
        return None

    def _check_condvar(self, node: ast.Call, op: str,
                       recv_lock: Tuple[str, bool, str],
                       held: List[_Held], in_while: int) -> None:
        lid = recv_lock[0]
        holds_cv = any(h.lockid == lid for h in held)
        if not holds_cv:
            self.findings.append(Finding(
                "RL004", self.path, node.lineno, node.col_offset,
                f"{op}() on condition {lid} without holding it — "
                f"RuntimeError at runtime, or a lost wakeup"))
        if op == "wait" and not in_while:
            self.findings.append(Finding(
                "RL004", self.path, node.lineno, node.col_offset,
                f"wait() on {lid} outside a while-predicate loop — "
                f"spurious wakeups make the predicate false on return; "
                f"re-test in a while (or use wait_for)"))
        if op in ("wait", "wait_for"):
            others = sorted({h.lockid for h in held
                             if h.lockid != lid})
            if others:
                self.facts.block_events.append(
                    (f"Condition.wait() on {lid}", tuple(
                        h for h in held if h.lockid != lid),
                     node.lineno, node.col_offset))

    def _check_wallclock(self, node: ast.expr) -> None:
        """RL006: time.time() as a direct operand of +/- arithmetic or
        a comparison — deadline/duration math on the wall clock."""
        is_arith = (isinstance(node, ast.BinOp)
                    and isinstance(node.op, (ast.Add, ast.Sub))) \
            or isinstance(node, ast.Compare)
        if not is_arith:
            return
        operands: List[ast.AST] = []
        if isinstance(node, ast.BinOp):
            operands = [node.left, node.right]
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
        for opnd in operands:
            if isinstance(opnd, ast.Call) \
                    and isinstance(opnd.func, ast.Attribute) \
                    and opnd.func.attr == "time" \
                    and _dotted(opnd.func.value) in \
                    self.ctx.mod.time_aliases:
                self.findings.append(Finding(
                    "RL006", self.path, opnd.lineno, opnd.col_offset,
                    "time.time() in deadline/duration arithmetic — "
                    "wall clock steps under NTP slew; use "
                    "time.monotonic() for timeouts"))


# ---------------------------------------------------------------------------
# whole-program passes
# ---------------------------------------------------------------------------

def _collect_functions(project: _Project) -> List[_FnCtx]:
    ctxs: List[_FnCtx] = []
    for mod in project.mods:
        for name, fn in mod.functions.items():
            ctxs.append(_FnCtx(project, mod, None, fn,
                               (mod.parts, None, name)))
        for cls in mod.classes.values():
            for name, fn in cls.methods.items():
                ctxs.append(_FnCtx(project, mod, cls, fn,
                                   (mod.parts, cls.name, name)))
                # nested defs (callbacks, thread bodies) get their own
                # facts — entry-held never applies to them
                for sub in ast.walk(fn):
                    if sub is not fn and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ctxs.append(_FnCtx(
                            project, mod, cls, sub,
                            (mod.parts, cls.name,
                             f"{name}.{sub.name}")))
    return ctxs


def _fn_label(key: _FnKey) -> str:
    mod = key[0][-1] if key[0] else "?"
    if key[1]:
        return f"{key[1]}.{key[2]}"
    return f"{mod}.{key[2]}"


def _fixpoint_acquires(project: _Project
                       ) -> Dict[_FnKey, Dict[str, Tuple[bool, str]]]:
    """lockid -> (timed, via) each function eventually acquires,
    propagated through resolved calls."""
    ev: Dict[_FnKey, Dict[str, Tuple[bool, str]]] = {}
    for key, facts in project.facts.items():
        d: Dict[str, Tuple[bool, str]] = {}
        for _held, h, _l, _c in facts.acquire_events:
            prev = d.get(h.lockid)
            if prev is None or (prev[0] and not h.timed):
                d[h.lockid] = (h.timed, "")
        ev[key] = d
    for _ in range(24):
        changed = False
        for key, facts in project.facts.items():
            d = ev[key]
            for keys, _vs, _held, _l, _c, _label in facts.call_events:
                for k2 in keys:
                    for lid, (timed, via) in ev.get(k2, {}).items():
                        nvia = f"via {_fn_label(k2)}()" \
                            if not via else f"via {_fn_label(k2)}() {via}"
                        prev = d.get(lid)
                        if prev is None:
                            d[lid] = (timed, nvia)
                            changed = True
                        elif prev[0] and not timed:
                            d[lid] = (timed, nvia)
                            changed = True
        if not changed:
            break
    return ev


def _fixpoint_blocking(project: _Project
                       ) -> Dict[_FnKey, Tuple[str, str]]:
    """First blocking operation each function eventually reaches
    (desc, via-chain), propagated through resolved calls."""
    ev: Dict[_FnKey, Tuple[str, str]] = {}
    for key, facts in project.facts.items():
        if facts.block_events:
            desc = facts.block_events[0][0]
            ev[key] = (desc, "")
    for _ in range(24):
        changed = False
        for key, facts in project.facts.items():
            if key in ev:
                continue
            for keys, _vs, _held, _l, _c, _label in facts.call_events:
                for k2 in keys:
                    if k2 in ev:
                        desc, via = ev[k2]
                        nvia = f"via {_fn_label(k2)}()" if not via \
                            else f"via {_fn_label(k2)}() {via}"
                        ev[key] = (desc, nvia)
                        changed = True
                        break
                if key in ev:
                    break
        if not changed:
            break
    return ev


def _fixpoint_entry_held(project: _Project
                         ) -> Dict[_FnKey, Optional[frozenset]]:
    """For each private method, the set of own-instance locks held at
    EVERY resolved self-call site (None = never observed called = no
    evidence either way; treated as guarded so never-called helpers
    don't flood RL001)."""
    entry: Dict[_FnKey, Optional[frozenset]] = {
        key: None for key, f in project.facts.items()
        if f.is_private and key[1] is not None}
    for _ in range(24):
        changed = False
        for key, facts in project.facts.items():
            caller_entry = entry.get(key)
            for keys, via_self, held, _l, _c, _label in facts.call_events:
                for k2 in keys:
                    if k2 not in entry:
                        continue
                    if via_self and key[1] is not None:
                        if caller_entry is None and key in entry:
                            # unconstrained caller: skip this site
                            continue
                        contrib = frozenset(
                            h.lockid for h in held if h.via_self)
                        if key in entry and caller_entry is not None:
                            contrib |= caller_entry
                    else:
                        contrib = frozenset()
                    cur = entry[k2]
                    new = contrib if cur is None else (cur & contrib)
                    if new != cur:
                        entry[k2] = new
                        changed = True
        if not changed:
            break
    return entry


def _check_lock_guards(project: _Project,
                       entry: Dict[_FnKey, Optional[frozenset]],
                       out: Dict[str, List[Finding]]) -> None:
    """RL001: per (class, attr), if some writes happen under an
    own-instance lock and others under none, flag the unguarded
    sites."""
    per_attr: Dict[Tuple[str, str], List[Tuple]] = {}
    for key, facts in project.facts.items():
        if facts.cls is None:
            continue
        extra: frozenset = frozenset()
        if key in entry:
            e = entry[key]
            if e is None:
                continue       # never-observed-called private helper
            extra = e
        for attr, self_locks, line, col in facts.write_events:
            if project.excluded_attr(facts.cls, attr):
                continue
            eff = self_locks | extra
            per_attr.setdefault((facts.cls.name, attr), []).append(
                (eff, facts.mod.path, line, col))
    for (cls_name, attr), events in per_attr.items():
        guarded = [e for e in events if e[0]]
        unguarded = [e for e in events if not e[0]]
        if not guarded or not unguarded:
            continue
        locks: Dict[str, int] = {}
        for eff, _p, _l, _c in guarded:
            for lid in eff:
                locks[lid] = locks.get(lid, 0) + 1
        guard = max(locks, key=lambda k: locks[k])
        for _eff, path, line, col in unguarded:
            out.setdefault(path, []).append(Finding(
                "RL001", path, line, col,
                f"'self.{attr}' written without {guard}, which guards "
                f"{len(guarded)} of {len(events)} writes to it in "
                f"{cls_name} — data-race candidate"))


class _Edge(NamedTuple):
    src: str
    dst: str
    path: str
    line: int
    col: int
    timed: bool
    via: str


def _collect_edges(project: _Project,
                   eventual: Dict[_FnKey, Dict[str, Tuple[bool, str]]]
                   ) -> List[_Edge]:
    edges: List[_Edge] = []
    for key, facts in project.facts.items():
        for held, h, line, col in facts.acquire_events:
            for hh in held:
                if hh.lockid != h.lockid:
                    edges.append(_Edge(hh.lockid, h.lockid,
                                       facts.mod.path, line, col,
                                       h.timed, ""))
        for keys, _vs, held, line, col, label in facts.call_events:
            if not held:
                continue
            for k2 in keys:
                for lid, (timed, via) in eventual.get(k2, {}).items():
                    for hh in held:
                        if hh.lockid != lid:
                            edges.append(_Edge(
                                hh.lockid, lid, facts.mod.path, line,
                                col, timed,
                                via or f"via {_fn_label(k2)}()"))
    return edges


def _check_lock_order(edges: List[_Edge],
                      out: Dict[str, List[Finding]]) -> None:
    """RL002's cycle half: Tarjan SCC over untimed cross-lock edges;
    every SCC with more than one lock is a potential deadlock."""
    graph: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], _Edge] = {}
    for e in edges:
        if e.timed or e.src == e.dst:
            continue
        graph.setdefault(e.src, set()).add(e.dst)
        graph.setdefault(e.dst, set())
        k = (e.src, e.dst)
        if k not in sites or (e.path, e.line) < (sites[k].path,
                                                 sites[k].line):
            sites[k] = e

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        cyc_edges = sorted(
            (sites[(a, b)] for (a, b) in sites
             if a in comp_set and b in comp_set),
            key=lambda e: (e.path, e.line, e.col))
        if not cyc_edges:
            continue
        anchor = cyc_edges[0]
        detail = "; ".join(
            f"{e.src} -> {e.dst} at {e.path}:{e.line}"
            + (f" ({e.via})" if e.via else "")
            for e in cyc_edges[:6])
        out.setdefault(anchor.path, []).append(Finding(
            "RL002", anchor.path, anchor.line, anchor.col,
            f"lock-order cycle over {{{', '.join(sorted(comp_set))}}} "
            f"— potential deadlock: {detail}"))


def _check_blocking(project: _Project,
                    blocking: Dict[_FnKey, Tuple[str, str]],
                    out: Dict[str, List[Finding]]) -> None:
    """RL003: blocking operations at sites where a lock is LEXICALLY
    held (the caller holding the lock owns the finding; callees are not
    re-flagged for their callers' locks)."""
    for key, facts in project.facts.items():
        path = facts.mod.path
        for desc, held, line, col in facts.block_events:
            if not held:
                continue
            locks = ", ".join(sorted({h.lockid for h in held}))
            out.setdefault(path, []).append(Finding(
                "RL003", path, line, col,
                f"blocking {desc} while holding {locks} — every other "
                f"thread contending on the lock stalls behind this"))
        for keys, _vs, held, line, col, label in facts.call_events:
            if not held:
                continue
            for k2 in keys:
                if k2 in blocking:
                    desc, via = blocking[k2]
                    locks = ", ".join(sorted({h.lockid for h in held}))
                    chain = f"{via} " if via else ""
                    out.setdefault(path, []).append(Finding(
                        "RL003", path, line, col,
                        f"call to {label}() reaches blocking {desc} "
                        f"({chain}while holding {locks})"))
                    break


def _check_thread_lifecycle(thread_ctors: List[Tuple],
                            out: Dict[str, List[Finding]]) -> None:
    """RL005: threads constructed without daemon=True and never joined
    anywhere in their module — they outlive shutdown."""
    for mod, call in thread_ctors:
        daemon = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        if daemon:
            continue
        target = None
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and node.value is call \
                    and len(node.targets) == 1:
                target = _last(node.targets[0])
        joined = daemoned = False
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                if target is None or _last(node.func.value) == target:
                    joined = True
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and node.targets[0].attr == "daemon":
                if target is None \
                        or _last(node.targets[0].value) == target:
                    daemoned = True
        if joined or daemoned:
            continue
        what = f"'{target}'" if target else "anonymous thread"
        out.setdefault(mod.path, []).append(Finding(
            "RL005", mod.path, call.lineno, call.col_offset,
            f"non-daemon thread {what} is never joined — it outlives "
            f"shutdown and wedges interpreter exit (set daemon=True "
            f"or join it on the shutdown path)"))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _analyze(mods: List[_Mod]) -> Tuple[Dict[str, List[Finding]],
                                        List[_Edge]]:
    project = _Project(mods)
    per_path: Dict[str, List[Finding]] = {m.path: [] for m in mods}
    thread_ctors: List[Tuple] = []
    for ctx in _collect_functions(project):
        facts = _FnFacts(ctx.key, ctx.node, ctx.cls, ctx.mod)
        project.facts[ctx.key] = facts
        lexical: List[Finding] = []
        _Analyzer(ctx, facts, lexical, thread_ctors).walk()
        per_path.setdefault(ctx.mod.path, []).extend(lexical)

    eventual = _fixpoint_acquires(project)
    blocking = _fixpoint_blocking(project)
    entry = _fixpoint_entry_held(project)
    edges = _collect_edges(project, eventual)

    _check_lock_guards(project, entry, per_path)
    _check_lock_order(edges, per_path)
    _check_blocking(project, blocking, per_path)
    _check_thread_lifecycle(thread_ctors, per_path)
    return per_path, edges


def _attr_type_pass(project_mods: List[_Mod]) -> None:
    """Cross-object attribute typing: ``r.engine = engine`` where ``r``
    is typed ``_Replica`` and ``engine`` is an ``Engine(...)`` records
    Engine as a candidate type for ``_Replica.engine``. Two rounds so a
    type learned in round one can feed a chain in round two."""
    project = _Project(project_mods)
    for _ in range(2):
        for mod in project_mods:
            fns: List[Tuple[Optional[_ClassInfo], str, ast.AST]] = \
                [(None, n, f) for n, f in mod.functions.items()]
            for cls in mod.classes.values():
                fns.extend((cls, n, f) for n, f in cls.methods.items())
            for cls, name, fn in fns:
                ctx = _FnCtx(project, mod, cls, fn,
                             (mod.parts, cls.name if cls else None,
                              name))

                def bind_attr(tgt: ast.AST, val: ast.AST) -> None:
                    if isinstance(tgt, ast.Attribute):
                        vtypes = {t for t in ctx.expr_types(val)
                                  if project.resolve_class(t)}
                        if not vtypes:
                            return
                        for rname in ctx.expr_types(tgt.value):
                            c = project.resolve_class(rname)
                            if c is not None:
                                c.attr_types.setdefault(
                                    tgt.attr, set()).update(vtypes)
                    elif isinstance(tgt, (ast.Tuple, ast.List)) \
                            and isinstance(val, (ast.Tuple, ast.List)) \
                            and len(tgt.elts) == len(val.elts):
                        for t, v in zip(tgt.elts, val.elts):
                            bind_attr(t, v)

                for node in _shallow_walk_body(fn):
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        bind_attr(node.targets[0], node.value)
                    elif isinstance(node, ast.AnnAssign) \
                            and isinstance(node.target, ast.Attribute):
                        names = _ann_class_names(node.annotation)
                        names = {n for n in names
                                 if project.resolve_class(n)}
                        if names:
                            for rname in ctx.expr_types(
                                    node.target.value):
                                c = project.resolve_class(rname)
                                if c is not None:
                                    c.attr_types.setdefault(
                                        node.target.attr,
                                        set()).update(names)


def _lint_mods(mods: List[_Mod]) -> List[Finding]:
    _attr_type_pass(mods)
    per_path, _edges = _analyze(mods)
    out: List[Finding] = []
    by_path = {m.path: m for m in mods}
    for path, findings in per_path.items():
        mod = by_path.get(path)
        src = mod.src if mod is not None else ""
        out.extend(lintcore.filter_findings(findings, src, "racelint",
                                            RULES))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    """Single-file mode (fixtures and tests) — same rules, no
    cross-module knowledge."""
    return _lint_mods([_Mod(path, src)])


def lint_file(path: Path) -> List[Finding]:
    return lint_source(path.read_text(encoding="utf-8"), str(path))


def lint_files(paths: Sequence[Path]) -> List[Finding]:
    """Project mode: whole-program analysis over every file (what
    ``main`` and the repo-clean test run). An unparseable file raises
    SyntaxError up front (``main`` reports per-file and lints the
    rest)."""
    return _lint_mods([_Mod(str(p), Path(p).read_text(encoding="utf-8"))
                       for p in paths])


def lock_order_edges(paths: Sequence[Path]) -> Set[Tuple[str, str]]:
    """The statically computed acquires-while-holding graph over
    ``paths`` as (held, acquired) lock-id pairs — including timed
    acquires, excluding same-lock (cross-instance) pairs. guards.py's
    LockOrderRecorder asserts the runtime-observed order is a subset of
    this set, which is how the static graph is validated by tests
    rather than trusted."""
    mods = [_Mod(str(p), Path(p).read_text(encoding="utf-8"))
            for p in paths]
    _attr_type_pass(mods)
    _per_path, edges = _analyze(mods)
    return {(e.src, e.dst) for e in edges if e.src != e.dst}


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="racelint",
        description="whole-program concurrency lint for the threaded "
                    "serve tier (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=["dalle_pytorch_tpu"],
                    help="files or directories (default: the package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--no-default-excludes", action="store_true",
                    help=f"also lint {DEFAULT_EXCLUDES} (the linters' "
                         f"own true-positive corpora)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (slug, desc) in sorted(RULES.items()):
            print(f"{rid}  {slug:22s} {desc}")
        return 0

    select = {r.strip().upper() for r in args.select.split(",")
              if r.strip()}
    ignore = {r.strip().upper() for r in args.ignore.split(",")
              if r.strip()}
    bad = (select | ignore) - set(RULES)
    if bad:
        print(f"racelint: unknown rule(s): {', '.join(sorted(bad))}",
              file=sys.stderr)
        return 2

    excludes = () if args.no_default_excludes else DEFAULT_EXCLUDES
    files = iter_py_files(args.paths, excludes)
    if not files:
        print("racelint: no python files found", file=sys.stderr)
        return 2

    mods: List[_Mod] = []
    errors = 0
    for f in files:
        try:
            mods.append(_Mod(str(f), f.read_text(encoding="utf-8")))
        except SyntaxError as e:
            errors += 1
            print(f"{f}:{e.lineno or 0}:0: parse error: {e.msg}",
                  file=sys.stderr)
    findings = _lint_mods(mods)
    if select:
        findings = [f for f in findings if f.rule in select]
    if ignore:
        findings = [f for f in findings if f.rule not in ignore]

    if args.as_json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "files": len(files)}, indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"racelint: {n} finding{'s' if n != 1 else ''} in "
              f"{len(files)} files", file=sys.stderr)
    if errors:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
