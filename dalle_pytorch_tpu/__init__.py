"""dalle_pytorch_tpu — a TPU-native (JAX/XLA/Pallas/pjit) text-to-image framework.

Re-implements, TPU-first, the full capability surface of the reference
DALLE-pytorch (HURU-School/DALLE-pytorch, fork of lucidrains/DALLE-pytorch
v0.0.36):

  * ``DiscreteVAE`` — conv encoder/decoder with a Gumbel-softmax discrete
    codebook (reference: dalle_pytorch/dalle_pytorch.py:65-157).
  * ``DALLE``       — joint text+image autoregressive transformer with
    per-position vocab masking, reversible blocks and block-sparse attention
    (reference: dalle_pytorch/dalle_pytorch.py:241-407).
  * ``CLIP``        — dual-encoder contrastive reranker
    (reference: dalle_pytorch/dalle_pytorch.py:161-237).

Unlike the reference — which is a torch/CUDA design — everything here is a
pure function over pytree parameters: jit/pjit-compiled, scan-over-layers,
Pallas kernels for attention, ``jax.sharding`` for data/tensor/sequence
parallelism, and stateless PRNG keys instead of device RNG snapshots.

The public API mirrors the reference's three exported names
(reference: dalle_pytorch/__init__.py:1) plus the functional layer beneath.
"""

__version__ = "0.1.0"

__all__ = [
    "DALLE",
    "CLIP",
    "DiscreteVAE",
    "DALLEConfig",
    "CLIPConfig",
    "VAEConfig",
]

_EXPORTS = {
    "DiscreteVAE": ("dalle_pytorch_tpu.models.vae", "DiscreteVAE"),
    "VAEConfig": ("dalle_pytorch_tpu.models.vae", "VAEConfig"),
    "DALLE": ("dalle_pytorch_tpu.models.dalle", "DALLE"),
    "DALLEConfig": ("dalle_pytorch_tpu.models.dalle", "DALLEConfig"),
    "CLIP": ("dalle_pytorch_tpu.models.clip", "CLIP"),
    "CLIPConfig": ("dalle_pytorch_tpu.models.clip", "CLIPConfig"),
}


def __getattr__(name):
    # Lazy exports keep `import dalle_pytorch_tpu.ops` free of model imports
    # (and of jax compilation work) until a model class is actually needed.
    if name in _EXPORTS:
        import importlib
        module, attr = _EXPORTS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
