"""Checkpoint/resume subsystem — params, optimizer state, and run metadata.

The reference persists bare ``state_dict`` weights once per epoch
(reference trainVAE.py:119, trainDALLE.py:212) and resumes with
``torch.load``+``load_state_dict`` (reference trainVAE.py:52-54,
trainDALLE.py:64-67,84-86, genDALLE.py:51-52,70-71, mixVAEcuda.py:20-21).
Optimizer state is NOT saved there — this build improves on that (SURVEY.md
§5.4) while keeping the same cross-program contract: ``train_vae`` writes a
checkpoint that ``train_dalle`` / ``gen_dalle`` / ``mix_vae`` read.

Format (a directory per step/epoch, atomic-rename commit):

    {dir}/{name}-{epoch}/
        manifest.json      # kind, epoch/step, model config as plain dict,
                           # extra metadata (temperature schedule state, ...)
        params.msgpack     # flax msgpack of the param pytree (bf16-safe)
        opt_state.msgpack  # optional; restored against optimizer.init(params)
        ema.msgpack        # optional (--ema_decay); f32 EMA of the params

Pytree leaves round-trip through ``flax.serialization`` msgpack (handles
dict/list/tuple trees of numpy/jax arrays including bfloat16). Restore pulls
arrays to host numpy; callers ``device_put``/shard as needed — checkpoints
stay layout-agnostic so a single-chip checkpoint restores onto any mesh.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import tempfile
import zlib
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

MANIFEST = "manifest.json"
PARAMS = "params.msgpack"
OPT_STATE = "opt_state.msgpack"
EMA = "ema.msgpack"


def _to_host(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _config_dict(config: Any) -> Any:
    """Dataclass config -> JSON-safe dict (recursively, so VAEConfig nested
    in DALLEConfig survives)."""
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return {f.name: _config_dict(getattr(config, f.name))
                for f in dataclasses.fields(config)}
    if isinstance(config, (list, tuple)):
        return list(_config_dict(c) for c in config)
    return config


def save(path: str, params, *, step: int = 0, config: Any = None,
         opt_state=None, kind: str = "model", meta: Optional[dict] = None,
         ema=None) -> str:
    """Write a checkpoint directory atomically (tmp dir + rename), so a
    killed writer never leaves a half-checkpoint that resume would trust.

    Multi-host: only process 0 writes (params are replicated under the dp
    meshes the CLIs build, so it holds the full tree); other processes
    return the path untouched — racing writers on a shared filesystem
    would corrupt the atomic-rename protocol."""
    from dalle_pytorch_tpu.parallel.multihost import is_primary
    if not is_primary():
        return path
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=parent, prefix=".ckpt-tmp-")
    try:
        payloads = {}

        def write_payload(fname: str, data: bytes) -> None:
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(data)
            # size + crc32 recorded in the manifest let ``validate`` prove
            # integrity WITHOUT msgpack-decoding multi-GB payloads twice
            payloads[fname] = {"bytes": len(data),
                               "crc32": zlib.crc32(data)}

        write_payload(PARAMS, serialization.msgpack_serialize(
            _to_host(params)))
        if opt_state is not None:
            write_payload(OPT_STATE, serialization.to_bytes(
                _to_host(opt_state)))
        if ema is not None:
            write_payload(EMA, serialization.msgpack_serialize(
                _to_host(ema)))
        manifest = {
            "kind": kind,
            "step": int(step),
            "config": _config_dict(config) if config is not None else None,
            "meta": meta or {},
            "payloads": payloads,
            "format": 1,
        }
        # manifest LAST: its presence then implies every payload above it
        # was fully written (tmp-dir scope; the rename below makes the
        # whole directory visible atomically either way)
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        # swap in with no window where neither old nor new exists: move the
        # old checkpoint aside, rename the new one in, then delete the old
        old = None
        if os.path.isdir(path):
            old = tempfile.mkdtemp(dir=parent, prefix=".ckpt-old-")
            os.rmdir(old)
            os.replace(path, old)
        os.replace(tmp, path)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def load_manifest(path: str) -> dict:
    with open(os.path.join(path, MANIFEST)) as f:
        return json.load(f)


def restore(path: str, opt_target=None) -> Tuple[Any, Any, dict]:
    """-> (params, opt_state | None, manifest).

    ``opt_target`` (usually ``optimizer.init(params)``) gives the structure
    the optimizer-state bytes restore into; None skips opt state even if the
    file exists.
    """
    manifest = load_manifest(path)
    with open(os.path.join(path, PARAMS), "rb") as f:
        params = serialization.msgpack_restore(f.read())
    opt_state = None
    opt_file = os.path.join(path, OPT_STATE)
    if opt_target is not None:
        if not os.path.exists(opt_file):
            raise FileNotFoundError(
                f"checkpoint {path} has no optimizer state to restore")
        with open(opt_file, "rb") as f:
            opt_state = serialization.from_bytes(opt_target, f.read())
    return params, opt_state, manifest


def restore_params(path: str) -> Tuple[Any, dict]:
    params, _, manifest = restore(path)
    return params, manifest


def restore_ema(path: str):
    """The checkpoint's EMA param tree (f32), or None when the checkpoint
    was written without ``--ema_decay`` (pre-EMA checkpoints included)."""
    ema_file = os.path.join(path, EMA)
    if not os.path.exists(ema_file):
        return None
    with open(ema_file, "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_train(path: str, optimizer) -> Tuple[Any, Any, dict]:
    """-> (params, opt_state | None, manifest) with ONE params read: the
    optimizer-state target is built from the just-restored params, and the
    opt file is decoded directly (no second restore() pass). opt_state is
    None when the checkpoint has no optimizer state (weights-only)."""
    manifest = load_manifest(path)
    with open(os.path.join(path, PARAMS), "rb") as f:
        params = serialization.msgpack_restore(f.read())
    opt_state = None
    opt_file = os.path.join(path, OPT_STATE)
    if os.path.exists(opt_file):
        with open(opt_file, "rb") as f:
            data = f.read()
        # decode in two steps so a corrupt/truncated file is not
        # misdiagnosed as a flag mismatch: msgpack_restore fails only on
        # bad bytes; from_state_dict fails only on tree-structure mismatch
        try:
            state_dict = serialization.msgpack_restore(data)
        except Exception as e:
            raise ValueError(
                f"optimizer state file {opt_file!r} is corrupt or "
                f"truncated — cannot decode its msgpack payload ({e}); "
                "restore from an older checkpoint or retrain") from e
        try:
            opt_state = serialization.from_state_dict(
                optimizer.init(params), state_dict)
        except (KeyError, ValueError) as e:
            # an opaque key/shape mismatch here means the optimizer's
            # state TREE differs from the one that wrote the checkpoint —
            # e.g. resuming with --clip_grad_norm toggled (optax.chain
            # adds a state entry). Same flags must be passed on resume.
            raise ValueError(
                f"optimizer state in {path!r} does not match this "
                "run's optimizer — resume with the same "
                "optimizer-shaping flags (e.g. --clip_grad_norm) "
                "the checkpoint was written with, or the file is from "
                f"an incompatible version ({e})") from e
    return params, opt_state, manifest


# ---------------------------------------------------------------------------
# validation — what "a checkpoint resume may trust" means
# ---------------------------------------------------------------------------

def validate(path: str) -> Tuple[bool, str]:
    """(ok, reason) — is ``path`` a checkpoint a resume may trust?

    A kill can only corrupt a checkpoint OUTSIDE the atomic-rename protocol
    (partial scp, disk-full truncation, a writer bypassing ``save``), but
    those cases are exactly the ones auto-resume must survive: a truncated
    ``params.msgpack`` or missing manifest falls through to the previous
    valid checkpoint instead of crashing the restarted run. Checks, in
    order: manifest present + parseable JSON dict, then each payload's
    size + crc32 against the manifest's ``payloads`` record (written by
    ``save`` — integrity without msgpack-decoding multi-GB tensors into
    host memory a second time). Pre-``payloads`` checkpoints fall back to
    full msgpack decode of every payload present."""
    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        return False, "missing manifest"
    except (ValueError, OSError) as e:
        return False, f"unreadable manifest: {e}"
    if not isinstance(manifest, dict):
        return False, "manifest is not an object"
    params_file = os.path.join(path, PARAMS)
    if not os.path.exists(params_file):
        return False, "missing params.msgpack"
    payloads = manifest.get("payloads")
    if isinstance(payloads, dict) and PARAMS in payloads:
        for fname, info in payloads.items():
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                return False, f"missing {fname}"
            if os.path.getsize(fpath) != info.get("bytes"):
                return False, (f"corrupt {fname}: size "
                               f"{os.path.getsize(fpath)} != recorded "
                               f"{info.get('bytes')}")
            crc = 0
            with open(fpath, "rb") as f:
                # chunked: a multi-GB payload must not materialize as one
                # bytes object on the memory-pressured restart path
                while chunk := f.read(1 << 22):
                    crc = zlib.crc32(chunk, crc)
            if crc != info.get("crc32"):
                return False, f"corrupt {fname}: crc32 mismatch"
        return True, "ok"
    for fname in (PARAMS, OPT_STATE, EMA):
        fpath = os.path.join(path, fname)
        if not os.path.exists(fpath):
            continue
        try:
            with open(fpath, "rb") as f:
                serialization.msgpack_restore(f.read())
        except Exception as e:
            return False, f"corrupt {fname}: {type(e).__name__}: {e}"
    return True, "ok"


# ---------------------------------------------------------------------------
# epoch-templated naming — the cross-CLI contract
# ---------------------------------------------------------------------------

def ckpt_path(models_dir: str, name: str, epoch: int) -> str:
    """``{models_dir}/{name}-{epoch}`` — the name-and-epoch template every
    CLI shares (reference trainVAE.py:119 writes ``{name}-{epoch}.pth``;
    trainDALLE.py:66 reads the same)."""
    return os.path.join(models_dir, f"{name}-{epoch}")


def latest(models_dir: str, name: str) -> Optional[Tuple[str, int]]:
    """Newest (path, epoch) for ``name`` under ``models_dir``, or None —
    resume-after-kill without remembering the epoch number."""
    if not os.path.isdir(models_dir):
        return None
    pat = re.compile(re.escape(name) + r"-(\d+)$")
    best = None
    for entry in os.listdir(models_dir):
        m = pat.match(entry)
        full = os.path.join(models_dir, entry)
        if m and os.path.isdir(full) and \
                os.path.exists(os.path.join(full, MANIFEST)):
            epoch = int(m.group(1))
            if best is None or epoch > best[1]:
                best = (full, epoch)
    return best


def latest_valid(models_dir: str, name: str):
    """Newest (path, epoch) for ``name`` that passes ``validate`` — the
    resume entry point when the newest checkpoint may be damaged (partial
    copy, truncation). Invalid candidates are skipped newest-first with a
    warning, falling back to the previous valid epoch; None when nothing
    valid exists."""
    if not os.path.isdir(models_dir):
        return None
    pat = re.compile(re.escape(name) + r"-(\d+)$")
    candidates = []
    for entry in os.listdir(models_dir):
        m = pat.match(entry)
        full = os.path.join(models_dir, entry)
        if m and os.path.isdir(full):
            candidates.append((int(m.group(1)), full))
    for epoch, full in sorted(candidates, reverse=True):
        ok, reason = validate(full)
        if ok:
            return full, epoch
        print(f"warning: skipping invalid checkpoint {full!r} ({reason})",
              flush=True)
    return None


# ---------------------------------------------------------------------------
# step-templated naming — mid-epoch supervisor checkpoints
# ---------------------------------------------------------------------------
# ``{name}-step{N}`` (N = completed optimizer steps) cannot collide with the
# epoch template's ``{name}-{digits}`` and stays invisible to ``latest``, so
# the cross-CLI contract (gen/mix read epoch checkpoints) is untouched; only
# the resilience auto-resume path reads these.

def step_ckpt_path(models_dir: str, name: str, step: int) -> str:
    return os.path.join(models_dir, f"{name}-step{step}")


def step_checkpoints(models_dir: str, name: str):
    """All (step, path) step checkpoints for ``name``, oldest first."""
    if not os.path.isdir(models_dir):
        return []
    pat = re.compile(re.escape(name) + r"-step(\d+)$")
    out = []
    for entry in os.listdir(models_dir):
        m = pat.match(entry)
        full = os.path.join(models_dir, entry)
        if m and os.path.isdir(full) and \
                os.path.exists(os.path.join(full, MANIFEST)):
            out.append((int(m.group(1)), full))
    return sorted(out)


def latest_valid_step(models_dir: str, name: str):
    """Newest (path, step) step checkpoint passing ``validate``, or None."""
    for step, full in reversed(step_checkpoints(models_dir, name)):
        ok, reason = validate(full)
        if ok:
            return full, step
        print(f"warning: skipping invalid checkpoint {full!r} ({reason})",
              flush=True)
    return None


def gc_steps(models_dir: str, name: str, keep: int) -> list:
    """Delete all but the newest ``keep`` step checkpoints (epoch
    checkpoints are never touched — they are the cross-CLI contract).
    Returns the removed paths. Multi-host: primary only, mirroring
    ``save``'s single-writer rule."""
    from dalle_pytorch_tpu.parallel.multihost import is_primary
    if not is_primary() or keep < 1:
        return []
    removed = []
    ckpts = step_checkpoints(models_dir, name)
    for _, full in ckpts[:max(len(ckpts) - keep, 0)]:
        shutil.rmtree(full, ignore_errors=True)
        removed.append(full)
    return removed


# ---------------------------------------------------------------------------
# config reconstruction
# ---------------------------------------------------------------------------

def vae_config_from_manifest(manifest: dict):
    from dalle_pytorch_tpu.models.vae import VAEConfig
    return VAEConfig(**manifest["config"])


def dalle_config_from_manifest(manifest: dict):
    from dalle_pytorch_tpu.models.dalle import DALLEConfig
    from dalle_pytorch_tpu.models.vae import VAEConfig
    cfg = dict(manifest["config"])
    cfg["vae"] = VAEConfig(**cfg["vae"])
    if isinstance(cfg.get("sparse_attn"), list):
        cfg["sparse_attn"] = tuple(cfg["sparse_attn"])
    return DALLEConfig(**cfg)
