"""Image IO: load/normalize images for training, save sample grids.

Replaces the reference's torchvision calls with PIL + numpy, producing NHWC
float32 — the layout the whole framework runs in (torch/torchvision are NCHW):

* ``load_image`` — read + resize + scale to [0,1] + normalize to [-1,1]
  (reference trainVAE.py:59-63 transform stack and trainDALLE.py:185-187
  ``read_image(...)/255.`` + Normalize(0.5, 0.5)).
* ``load_image_batch`` — the per-path minibatch fetch loop
  (reference trainDALLE.py:180-188), vectorized into one NHWC array.
* ``save_image_grid`` — row-major tiling + renormalization to PNG, the
  ``torchvision.utils.save_image(..., normalize=True)`` equivalent used for
  recon grids and samples (reference trainVAE.py:109-114,
  trainDALLE.py:215-217, mixVAEcuda.py:48-55).
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence

import numpy as np

try:
    from PIL import Image
except ImportError:  # pragma: no cover - PIL is in the base image
    Image = None


def _require_pil():
    if Image is None:
        raise ImportError("PIL is required for image IO")


_NATIVE_EXTS = {".png", ".jpg", ".jpeg"}


def _native_loader():
    """The C++ threaded decoder (native/loader.cc), or None when it can't
    build/load or DALLE_TPU_NATIVE_LOADER=0. Decode output matches the PIL
    path (exact for decode, within PIL's 8-bit rounding for resize)."""
    if os.environ.get("DALLE_TPU_NATIVE_LOADER", "1") == "0":
        return None
    from dalle_pytorch_tpu import native
    return native.load_image_batch_native if native.available() else None


def _load_batch_fast(paths: Sequence[str],
                     image_size: Optional[int]) -> Optional[np.ndarray]:
    """Batch-decode via the native loader when every file is JPEG/PNG;
    None -> caller uses the PIL path."""
    if not paths or any(os.path.splitext(p)[1].lower() not in _NATIVE_EXTS
                        for p in paths):
        return None
    fn = _native_loader()
    if fn is None:
        return None
    try:
        return fn(list(paths), image_size or 0)
    except RuntimeError:
        return None  # e.g. CMYK jpeg corner case: PIL path decides


def load_image(path: str, image_size: Optional[int] = None) -> np.ndarray:
    """-> (H, W, 3) float32 in [-1, 1]."""
    _require_pil()
    img = Image.open(path).convert("RGB")
    if image_size is not None and img.size != (image_size, image_size):
        img = img.resize((image_size, image_size), Image.BILINEAR)
    arr = np.asarray(img, dtype=np.float32) / 255.0
    return arr * 2.0 - 1.0


def load_image_batch(paths: Sequence[str], data_path: str = "",
                     image_size: Optional[int] = None,
                     subdir: str = "0") -> np.ndarray:
    """Fetch a minibatch of images by filename -> (b, H, W, 3) in [-1, 1].

    Filenames resolve under ``{data_path}/{subdir}/{filename}`` — the
    reference's ImageFolder-style single-class layout (reference
    trainDALLE.py:185 'images are expected to be in ./imagefolder/0/').
    Absolute paths and paths that already exist are used as-is.
    """
    full_paths = []
    for p in paths:
        full = p
        if not os.path.isabs(p) and not os.path.exists(p):
            full = os.path.join(data_path, subdir, p)
        full_paths.append(full)
    fast = _load_batch_fast(full_paths, image_size)
    if fast is not None:
        return fast
    return np.stack([load_image(p, image_size) for p in full_paths])


def list_image_folder(root: str) -> List[str]:
    """All image files under an ImageFolder-style root (class subdirs, or a
    flat dir), sorted — the torchvision ``datasets.ImageFolder`` file walk
    (reference trainVAE.py:65-67) without the unused class labels."""
    exts = {".png", ".jpg", ".jpeg", ".bmp", ".webp"}
    files = []
    for dirpath, _, names in os.walk(root):
        for n in sorted(names):
            if os.path.splitext(n)[1].lower() in exts:
                files.append(os.path.join(dirpath, n))
    return sorted(files)


class ImageFolderDataset:
    """Minimal ImageFolder: fixed-size shuffled batches of normalized NHWC
    images (reference trainVAE.py:59-67 DataLoader over ImageFolder)."""

    def __init__(self, root: str, image_size: int, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True):
        self.files = list_image_folder(root)
        if not self.files:
            raise FileNotFoundError(f"no images under {root!r}")
        self.image_size = image_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last

    def __len__(self) -> int:
        n = len(self.files)
        if self.drop_last and n >= self.batch_size:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def epoch(self, epoch: int = 0):
        order = np.arange(len(self.files))
        if self.shuffle:
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        for b in range(len(self)):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size:  # wrap ragged tail
                idx = np.concatenate([idx, order[:self.batch_size - len(idx)]])
            batch_paths = [self.files[i] for i in idx]
            fast = _load_batch_fast(batch_paths, self.image_size)
            if fast is not None:
                yield fast
            else:
                yield np.stack([load_image(p, self.image_size)
                                for p in batch_paths])

    def __iter__(self):
        return self.epoch(0)


def to_uint8(images: np.ndarray, normalize: bool = True) -> np.ndarray:
    """(..., H, W, C) float -> uint8. ``normalize=True`` rescales by the
    batch min/max like torchvision save_image(normalize=True); otherwise
    assumes [-1, 1]."""
    x = np.asarray(images, dtype=np.float32)
    if normalize:
        lo, hi = float(x.min()), float(x.max())
        x = (x - lo) / max(hi - lo, 1e-8)
    else:
        x = (x + 1.0) / 2.0
    return (np.clip(x, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)


def save_image_grid(images: np.ndarray, path: str, nrow: int = 8,
                    normalize: bool = True, padding: int = 2) -> None:
    """Tile (b, H, W, C) into a row-major grid PNG — the save_image
    equivalent for recon grids and samples. Multi-host: process 0 only."""
    from dalle_pytorch_tpu.parallel.multihost import is_primary
    if not is_primary():
        return
    _require_pil()
    x = to_uint8(images, normalize=normalize)
    b, h, w, c = x.shape
    ncol = min(nrow, b)
    nrows = math.ceil(b / ncol)
    grid = np.zeros((nrows * (h + padding) + padding,
                     ncol * (w + padding) + padding, c), np.uint8)
    for i in range(b):
        r, col = divmod(i, ncol)
        y0 = r * (h + padding) + padding
        x0 = col * (w + padding) + padding
        grid[y0:y0 + h, x0:x0 + w] = x[i]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    Image.fromarray(grid.squeeze() if c == 1 else grid).save(path)
