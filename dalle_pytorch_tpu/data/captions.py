"""Caption-file parsing and padded text batching.

File contracts (reference trainDALLE.py:92-163, SURVEY.md §5 "data
contract"):

* ``od-captionsonly.txt`` — one caption per line; builds the vocabulary in
  line order (reference trainDALLE.py:96-111).
* ``od-captions.txt`` — lines of ``image_filename : caption``; filenames are
  resolved under ``{data_path}/0/{filename}`` by the image loader
  (reference trainDALLE.py:113-125,185).
* captions are tokenized by splitting on single spaces, '' tokens skipped,
  and padded with PAD=0 to ``text_seq_len`` (reference
  trainDALLE.py:118-122,155-157).

``CaptionDataset`` is the TPU-shaped replacement for the reference's
``ImageCaptions`` iterator (reference trainDALLE.py:135-163): it yields
fixed-size ``(paths, int32 token array)`` minibatches — fixed batch shape so
the jit train step compiles once (the reference's ragged final batch would
retrace; we drop or wrap it instead).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from dalle_pytorch_tpu.data.vocabulary import PAD_TOKEN, Vocabulary


def read_captions_only(path: str) -> List[str]:
    """Lines of the captions-only corpus, newline kept off. The reference
    appends raw lines (with '\\n') to the vocab — split(' ') then treats
    'word\\n' as a distinct token; we strip instead (deliberate fix, flagged:
    strips trailing newlines so 'dog' == 'dog\\n')."""
    with open(path) as f:
        return [line.rstrip("\n") for line in f if line.strip()]


def read_caption_pairs(path: str) -> List[Tuple[str, str]]:
    """``filename : caption`` pairs (reference trainDALLE.py:113-125).
    Splits on the FIRST ':' (filenames with colons are not supported by the
    reference either) and strips surrounding whitespace."""
    pairs = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            fn, _, txt = line.partition(":")
            pairs.append((fn.strip(), txt.strip("\n")))
    return pairs


def encode_pairs(pairs: Sequence[Tuple[str, str]], vocab: Vocabulary,
                 text_seq_len: int) -> List[Tuple[str, List[int]]]:
    """(filename, caption) -> (filename, padded ids). OOV raises KeyError —
    same hard failure as the reference (Vocabulary.py:43)."""
    return [(fn, vocab.encode(txt, pad_to=text_seq_len)) for fn, txt in pairs]


@dataclasses.dataclass
class CaptionDataset:
    """Deterministic epoch iterator over (paths, padded-token) minibatches.

    Unlike the reference iterator (trainDALLE.py:135-163) every yielded batch
    has exactly ``batch_size`` rows: when ``drop_last`` is False the tail
    batch wraps around to the epoch head so the jit step never sees a new
    batch shape. ``shuffle`` uses a seeded numpy Generator (stateless across
    epochs via ``epoch`` salt) — host-side RNG, never device RNG.
    """

    data: List[Tuple[str, List[int]]]
    batch_size: int = 4
    shuffle: bool = False
    seed: int = 0
    drop_last: bool = False

    def __len__(self) -> int:
        n = len(self.data)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def epoch(self, epoch: int = 0):
        """Yields (list of paths, (batch_size, text_seq_len) int32 array)."""
        order = np.arange(len(self.data))
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch))
            rng.shuffle(order)
        n_batches = len(self)
        for b in range(n_batches):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size:  # wrap the ragged tail
                idx = np.concatenate(
                    [idx, order[:self.batch_size - len(idx)]])
            paths = [self.data[i][0] for i in idx]
            toks = np.asarray([self.data[i][1] for i in idx],
                              dtype=np.int32)
            yield paths, toks

    def __iter__(self):
        return self.epoch(0)


def load_caption_data(captions_only_path: str, caption_pairs_path: str,
                      text_seq_len: int,
                      vocab: Optional[Vocabulary] = None):
    """One-call data setup mirroring trainDALLE's preamble (reference
    trainDALLE.py:92-133): build (or reuse) the vocab from the captions-only
    corpus, then encode the (filename, caption) pairs.

    Returns (vocab, [(filename, padded ids), ...]).
    """
    if vocab is None:
        vocab = Vocabulary.from_captions(
            read_captions_only(captions_only_path))
    pairs = read_caption_pairs(caption_pairs_path)
    return vocab, encode_pairs(pairs, vocab, text_seq_len)


def text_mask(tokens: np.ndarray) -> np.ndarray:
    """Padding mask (True = real token). The reference passes an all-True
    mask in training (trainDALLE.py:192) — callers choose; this gives the
    semantically-correct mask for PAD=0 padded batches."""
    return tokens != PAD_TOKEN
