"""L5 data layer: vocabulary, caption parsing, image IO, prefetch.

TPU-native replacement for the reference's data stack (Vocabulary.py,
trainDALLE.py:92-163 caption pipeline, torchvision ImageFolder/read_image) —
NHWC numpy on the host, background prefetch onto the device mesh.
"""

from dalle_pytorch_tpu.data.captions import (CaptionDataset, encode_pairs,
                                             load_caption_data,
                                             read_caption_pairs,
                                             read_captions_only, text_mask)
from dalle_pytorch_tpu.data.images import (ImageFolderDataset, load_image,
                                           load_image_batch,
                                           list_image_folder,
                                           save_image_grid, to_uint8)
from dalle_pytorch_tpu.data.prefetch import Prefetcher, prefetch, \
    shard_for_host
from dalle_pytorch_tpu.data.vocabulary import (EOS_TOKEN, PAD_TOKEN,
                                               SOS_TOKEN, Vocabulary)

__all__ = [
    "Vocabulary", "PAD_TOKEN", "SOS_TOKEN", "EOS_TOKEN",
    "CaptionDataset", "load_caption_data", "read_caption_pairs",
    "read_captions_only", "encode_pairs", "text_mask",
    "ImageFolderDataset", "load_image", "load_image_batch",
    "list_image_folder", "save_image_grid", "to_uint8",
    "Prefetcher", "prefetch", "shard_for_host",
]
