"""Host-side prefetching and host-sharded data feeding.

The reference reads every image synchronously inside the train loop
(reference trainDALLE.py:182-187) — a host-bound stall between every step.
SURVEY.md §7 (hard part e) requires the TPU pipeline to overlap host IO with
device compute instead:

* ``Prefetcher`` — a daemon-thread pipeline that stays ``depth`` batches
  ahead of the consumer, moving each batch to device (optionally with a
  ``NamedSharding``) so the next step's inputs are already resident when the
  current step retires. With jax's async dispatch this keeps the chip fed as
  long as host IO for one batch is faster than one train step.
* ``shard_for_host`` — multi-host data sharding: each process takes its
  contiguous slice of the example list, so a v5e-64-style multi-host job
  reads 1/num_hosts of the data per host (the standard jax.process_index
  recipe; collectives then see a globally-sharded batch).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax


def shard_for_host(items: Sequence[Any],
                   process_index: Optional[int] = None,
                   process_count: Optional[int] = None) -> Sequence[Any]:
    """Contiguous per-host slice of a dataset (equal-length across hosts,
    trailing remainder dropped so every host steps in lockstep)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    per = len(items) // pc
    if per == 0:
        raise ValueError(f"{len(items)} items cannot feed {pc} hosts")
    return items[pi * per:(pi + 1) * per]


class Prefetcher:
    """Wraps a host batch iterator; yields device-resident batches.

    ``transform`` runs in the worker thread (e.g. the per-batch image file
    reads), so disk + decode overlap device compute. ``sharding`` device_puts
    each batch with a NamedSharding (global array for pjit); None leaves the
    put to jit's default device placement.
    """

    _DONE = object()

    def __init__(self, it: Iterable, depth: int = 2,
                 transform: Optional[Callable[[Any], Any]] = None,
                 sharding=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._transform = transform
        self._sharding = sharding
        self._thread = threading.Thread(
            target=self._worker, args=(iter(it),), daemon=True)
        self._thread.start()

    def _worker(self, it: Iterator):
        try:
            for batch in it:
                if self._transform is not None:
                    batch = self._transform(batch)
                # multi-host: keep batches on the HOST — shard_batch
                # assembles the global array from each process's local data
                # (a premature local device_put would just be pulled back)
                if jax.process_count() > 1:
                    pass
                elif self._sharding is not None:
                    batch = jax.tree.map(
                        lambda x: jax.device_put(x, self._sharding), batch)
                else:
                    batch = jax.tree.map(jax.device_put, batch)
                self._q.put(batch)
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


def prefetch(it: Iterable, depth: int = 2,
             transform: Optional[Callable[[Any], Any]] = None,
             sharding=None) -> Prefetcher:
    """Convenience wrapper: ``for batch in prefetch(dataset.epoch(e)): ...``"""
    return Prefetcher(it, depth=depth, transform=transform,
                      sharding=sharding)
