"""Host-side prefetching and host-sharded data feeding.

The reference reads every image synchronously inside the train loop
(reference trainDALLE.py:182-187) — a host-bound stall between every step.
SURVEY.md §7 (hard part e) requires the TPU pipeline to overlap host IO with
device compute instead:

* ``Prefetcher`` — a daemon-thread pipeline that stays ``depth`` batches
  ahead of the consumer, moving each batch to device (optionally with a
  ``NamedSharding``) so the next step's inputs are already resident when the
  current step retires. With jax's async dispatch this keeps the chip fed as
  long as host IO for one batch is faster than one train step.
* ``shard_for_host`` — multi-host data sharding: each process takes its
  contiguous slice of the example list, so a v5e-64-style multi-host job
  reads 1/num_hosts of the data per host (the standard jax.process_index
  recipe; collectives then see a globally-sharded batch).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax


def shard_for_host(items: Sequence[Any],
                   process_index: Optional[int] = None,
                   process_count: Optional[int] = None) -> Sequence[Any]:
    """Contiguous per-host slice of a dataset (equal-length across hosts,
    trailing remainder dropped so every host steps in lockstep)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    per = len(items) // pc
    if per == 0:
        raise ValueError(f"{len(items)} items cannot feed {pc} hosts")
    return items[pi * per:(pi + 1) * per]


class Prefetcher:
    """Wraps a host batch iterator; yields device-resident batches.

    ``transform`` runs in the worker thread (e.g. the per-batch image file
    reads), so disk + decode overlap device compute. ``sharding`` device_puts
    each batch with a NamedSharding (global array for pjit); None leaves the
    put to jit's default device placement.

    Data-path resilience (docs/RESILIENCE.md):
      * a worker exception is re-raised on the CONSUMER side, after the
        already-queued good batches drain — never swallowed;
      * ``max_bad_records`` > 0 skips up to that many records whose
        transform/device-put fails (one unreadable image must not kill an
        11-hour run), counting them (``self.bad_records``) and reporting
        each through ``on_event``; record N+1 propagates;
      * ``iterator_retries`` > 0 retries ``next()`` on the SOURCE after an
        exception, for iterators wrapping transient backends (a raised
        GENERATOR is closed and yields StopIteration on retry, so the
        default stays 0: propagate — silent truncation is worse than a
        crash);
      * a worker thread that dies without posting its sentinel (hard kill)
        is detected by the consumer and restarted once from the shared
        iterator.
    """

    _DONE = object()

    def __init__(self, it: Iterable, depth: int = 2,
                 transform: Optional[Callable[[Any], Any]] = None,
                 sharding=None, max_bad_records: int = 0,
                 iterator_retries: int = 0,
                 on_event: Optional[Callable[[dict], None]] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._transform = transform
        self._sharding = sharding
        self._max_bad = max(int(max_bad_records), 0)
        self._it_retries = max(int(iterator_retries), 0)
        self._on_event = on_event
        self._it = iter(it)
        self.bad_records = 0
        self.iterator_retries = 0
        # source records consumed up to AND INCLUDING the last batch this
        # consumer received (bad skipped records counted) — what a mid-epoch
        # resume must skip to replay nothing: with max_bad_records > 0 the
        # trained-step count alone undercounts the source position
        self.source_pos = 0
        # the worker's own running position — an attribute (not a worker
        # local) so a restarted worker resumes counting where the dead one
        # stopped instead of resetting and corrupting source_pos
        self._worker_pos = 0
        self._thread_restarts_left = 1
        self._start_worker()

    def _start_worker(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _emit(self, kind: str, **fields) -> None:
        if self._on_event is None:
            return
        from dalle_pytorch_tpu.utils.metrics import structured_event
        try:
            self._on_event(structured_event(kind, **fields))
        except Exception:
            pass                  # an event sink must never kill the feed

    def _worker(self):
        it = self._it
        pos = self._worker_pos        # source records consumed by the worker
        try:
            while True:
                try:
                    batch = next(it)
                except StopIteration:
                    return
                except BaseException as e:
                    if self.iterator_retries < self._it_retries:
                        self.iterator_retries += 1
                        self._emit("prefetch_iterator_retry",
                                   error=f"{type(e).__name__}: {e}",
                                   retry=self.iterator_retries)
                        continue
                    self._err = e
                    return
                pos += 1
                self._worker_pos = pos
                try:
                    if self._transform is not None:
                        batch = self._transform(batch)
                    # multi-host: keep batches on the HOST — shard_batch
                    # assembles the global array from each process's local
                    # data (a premature local device_put would just be
                    # pulled back)
                    if jax.process_count() > 1:
                        pass
                    elif self._sharding is not None:
                        batch = jax.tree.map(
                            lambda x: jax.device_put(x, self._sharding),
                            batch)
                    else:
                        batch = jax.tree.map(jax.device_put, batch)
                except BaseException as e:
                    if self.bad_records < self._max_bad:
                        self.bad_records += 1
                        self._emit("prefetch_bad_record",
                                   error=f"{type(e).__name__}: {e}",
                                   skipped=self.bad_records,
                                   cap=self._max_bad)
                        continue
                    self._err = e
                    return
                # pair each batch with the worker's source position so the
                # consumer's view (source_pos) never runs ahead of what it
                # actually received — the worker may be several records
                # (including skipped bad ones) past the queue head
                self._q.put((pos, batch))
        finally:
            self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if self._thread.is_alive():
                    continue
                # sentinel pending in the queue: loop once more to take it
                if not self._q.empty():
                    continue
                # the worker died WITHOUT its finally-sentinel (hard kill,
                # interpreter teardown race): restart it once from the
                # shared iterator, then give up loudly — a silently dead
                # feed would hang the train loop forever
                if self._thread_restarts_left > 0:
                    self._thread_restarts_left -= 1
                    self._emit("prefetch_restart")
                    self._start_worker()
                    continue
                raise RuntimeError(
                    "prefetch worker died without reporting an error "
                    "(restart already spent)")
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        self.source_pos, batch = item
        return batch


def prefetch(it: Iterable, depth: int = 2,
             transform: Optional[Callable[[Any], Any]] = None,
             sharding=None, max_bad_records: int = 0,
             iterator_retries: int = 0,
             on_event: Optional[Callable[[dict], None]] = None) -> Prefetcher:
    """Convenience wrapper: ``for batch in prefetch(dataset.epoch(e)): ...``"""
    return Prefetcher(it, depth=depth, transform=transform,
                      sharding=sharding, max_bad_records=max_bad_records,
                      iterator_retries=iterator_retries, on_event=on_event)
