"""Word-level vocabulary with reserved PAD/SOS/EOS ids.

Behavior parity with the reference ``Vocabulary`` (reference
Vocabulary.py:3-43): PAD=0, SOS=1, EOS=2 reserved, real words numbered from 3
in first-seen order; ``to_index`` raises ``KeyError`` on out-of-vocabulary
words (the reference's documented hard failure mode, SURVEY.md §5.3).

Additions over the reference (cross-CLI reproducibility): deterministic
round-trip ``save``/``load`` to JSON so the generation CLI can rebuild the
exact training vocab from a file instead of re-reading the caption corpus,
and ``encode``/``decode`` helpers for padded id sequences.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

PAD_TOKEN = 0
SOS_TOKEN = 1
EOS_TOKEN = 2
_RESERVED = {PAD_TOKEN: "PAD", SOS_TOKEN: "SOS", EOS_TOKEN: "EOS"}


class Vocabulary:
    """Maps words <-> integer ids (reference Vocabulary.py:3-43)."""

    def __init__(self, name: str = "captions"):
        self.name = name
        self.word2index: Dict[str, int] = {}
        self.word2count: Dict[str, int] = {}
        self.index2word: Dict[int, str] = dict(_RESERVED)
        self.num_words = 3
        self.num_sentences = 0
        self.longest_sentence = 0

    def add_word(self, word: str) -> None:
        if word not in self.word2index:
            self.word2index[word] = self.num_words
            self.word2count[word] = 1
            self.index2word[self.num_words] = word
            self.num_words += 1
        else:
            self.word2count[word] += 1

    def add_sentence(self, sentence: str) -> None:
        """Split on single spaces, exactly like the reference tokenizer
        (reference trainDALLE.py:107-108, Vocabulary.py:28-37)."""
        words = sentence.split(" ")
        for word in words:
            self.add_word(word)
        if len(words) > self.longest_sentence:
            self.longest_sentence = len(words)
        self.num_sentences += 1

    def to_word(self, index: int) -> str:
        return self.index2word[index]

    def to_index(self, word: str) -> int:
        """KeyError on OOV — reference contract (Vocabulary.py:43)."""
        return self.word2index[word]

    def __len__(self) -> int:
        return self.num_words

    def __contains__(self, word: str) -> bool:
        return word in self.word2index

    # -- id-sequence helpers -------------------------------------------------

    def encode(self, text: str, pad_to: Optional[int] = None,
               skip_empty: bool = True) -> List[int]:
        """Text -> ids; pads with PAD=0 to ``pad_to`` when given.

        ``skip_empty`` drops the '' tokens double spaces produce, as the
        training-script tokenizer loop does (reference trainDALLE.py:118-122).
        OOV raises KeyError like ``to_index``.
        """
        ids = [self.to_index(w) for w in text.split(" ")
               if not (skip_empty and w == "")]
        if pad_to is not None:
            if len(ids) > pad_to:
                raise ValueError(
                    f"caption has {len(ids)} tokens > pad_to={pad_to}")
            ids = ids + [PAD_TOKEN] * (pad_to - len(ids))
        return ids

    def decode(self, ids, strip_pad: bool = True) -> str:
        words = [self.to_word(int(i)) for i in ids
                 if not (strip_pad and int(i) == PAD_TOKEN)]
        return " ".join(words)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        """JSON round-trip; word order (= id order) is preserved because
        word2index insertion order is id order."""
        payload = {
            "name": self.name,
            "words": sorted(self.word2index, key=self.word2index.get),
            "counts": self.word2count,
            "num_sentences": self.num_sentences,
            "longest_sentence": self.longest_sentence,
        }
        with open(path, "w") as f:
            json.dump(payload, f)

    @classmethod
    def load(cls, path: str) -> "Vocabulary":
        with open(path) as f:
            payload = json.load(f)
        vocab = cls(payload["name"])
        for word in payload["words"]:
            vocab.add_word(word)
        vocab.word2count = {k: int(v) for k, v in payload["counts"].items()}
        vocab.num_sentences = int(payload["num_sentences"])
        vocab.longest_sentence = int(payload["longest_sentence"])
        return vocab

    @classmethod
    def from_captions(cls, captions, name: str = "captions") -> "Vocabulary":
        """Build from an iterable of caption strings — the trainDALLE
        vocabulary construction (reference trainDALLE.py:96-111)."""
        vocab = cls(name)
        for caption in captions:
            vocab.add_sentence(caption)
        return vocab
