"""Transport layer for process- and host-isolated replica serving.

``serve/ipc.py`` speaks a framed, versioned, sequence-numbered,
CRC-checksummed protocol; this module is everything UNDER the frames —
how frame bytes move between the parent and a worker. Two transports
share one contract (``send_bytes`` / ``poll`` / ``recv_bytes``, the
``multiprocessing.Connection`` surface the IPC layer was already written
against):

  * ``PipeTransport`` — a duplex ``multiprocessing`` pipe. The OS
    delivers each write whole, the peer is a local child by
    construction, and there is no network to lie about. This is
    ``--transport pipe``, the process-isolation default (PR 8).
  * ``SocketTransport`` — a TCP stream, which is what host-per-engine
    isolation actually crosses. A stream transport has failure modes a
    pipe can never exhibit, and each one must surface as a TYPED error
    rather than a hang or a silent mis-parse:

      - **short reads**: a frame legally arrives in arbitrary byte
        fragments; the receive path buffers and loops to the exact
        length-prefixed frame boundary before handing bytes up;
      - **mid-frame EOF / torn frames**: a peer dying between two
        writes leaves a partial frame — ``IPCError``, never a partial
        parse (the CRC would catch it, but the transport refuses to
        even offer the bytes);
      - **connection reset**: an RST mid-stream is
        ``IPCError`` when it tears a frame, ``ConnectionResetError``
        at a frame boundary — either way the replica is fenced, and a
        remote worker (no PID to probe) is declared dead off exactly
        this signal;
      - **stalled peers**: every receive is buffered + non-blocking
        (``poll`` uses ``select``), so a socket that is accepted but
        never written — or a frame that stops halfway — can stall a
        HEARTBEAT deadline but never a thread; sends time out
        (``BrokenPipeError``) instead of blocking forever on a peer
        that stopped reading.

``WorkerListener`` is the parent's dial-in endpoint: workers CONNECT TO
THE PARENT (never the reverse — the parent may be behind the same
firewall, and a dialing worker composes with hand-started remote
workers), and the first frame on a new connection must be an
authenticated HELLO: the shared token (serve/auth.py's constant-time
``check_token``; ships
via the ``DALLE_WORKER_TOKEN`` env var, never argv) plus the protocol
version and the replica index the worker claims. A bad token, a version
skew, or an unexpected index closes the connection without attaching
anything. On success the parent answers HELLO_OK and streams the worker
spec (params + config, pickled) down the SAME authenticated socket —
so a remote worker needs nothing but the endpoint, the token, and an
index: ``python -m dalle_pytorch_tpu.serve.worker --connect HOST:PORT
--index N``. Only the worker ever unpickles, and only from the endpoint
its operator pointed it at; the parent parses nothing but JSON frames
off the network.
"""

from __future__ import annotations

import os
import pickle
import secrets
import select
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from dalle_pytorch_tpu.serve import auth

# the env var a hand-started / launcher-started worker reads its HELLO
# token from — an env var, not argv, so the secret never shows in `ps`
TOKEN_ENV = "DALLE_WORKER_TOKEN"

# length prefix for socket framing; the cap bounds what a garbage or
# hostile length field can make the receive buffer allocate
_LEN = struct.Struct("<I")
MAX_FRAME_BYTES = 1 << 30


class IPCError(RuntimeError):
    """A frame or stream that cannot be believed: truncated, wrong
    magic, version skew, checksum mismatch, broken sequence,
    unparseable payload, mid-frame EOF, or a reset that tore a frame.
    The only safe response is to FENCE the peer — a stream that
    produced one lie may have corrupted anything."""


class PipeTransport:
    """A ``multiprocessing`` duplex pipe behind the transport contract.
    The pipe already delivers whole messages and raises ``EOFError`` /
    ``OSError`` when the peer vanishes; this wrapper only adds the
    metadata (`kind`/`peer`) the observability surface reports."""

    kind = "pipe"

    def __init__(self, conn):
        self._conn = conn
        self._closed = False
        self.peer = "pipe"

    def send_bytes(self, data: bytes) -> None:
        self._conn.send_bytes(data)

    def poll(self, timeout: float = 0.0) -> bool:
        if self._closed:
            return False
        return self._conn.poll(timeout)

    def recv_bytes(self) -> bytes:
        return self._conn.recv_bytes()

    def alive(self) -> bool:
        # a pipe's liveness is its process's liveness; the owner layers
        # PID checks on top, so the transport only reports local close
        return not self._closed

    def state_desc(self) -> str:
        return "closed" if self._closed else "open"

    def close(self) -> None:
        self._closed = True
        try:
            self._conn.close()
        except (OSError, AttributeError):
            pass


class SocketTransport:
    """A TCP stream behind the transport contract, framed as
    ``[u32 little-endian length][frame bytes]``.

    All receiving is buffered and non-blocking: ``poll`` selects, then
    drains the socket into a local buffer; ``recv_bytes`` hands back one
    complete frame from that buffer or raises — ``EOFError`` for a
    clean FIN at a frame boundary, ``IPCError`` for EOF/reset with a
    partial frame buffered (the torn-frame signal), and
    ``ConnectionResetError`` for an RST at a boundary. No call here can
    block past ``poll``'s timeout, which is what keeps a stalled peer a
    heartbeat problem instead of a wedged control thread.

    Sends loop over ``select`` with a deadline and raise
    ``BrokenPipeError`` when the peer stops draining — a worker treats
    that exactly like a dead parent (exit, leak nothing), the parent
    records it and lets supervision fence the replica."""

    kind = "socket"

    def __init__(self, sock: socket.socket, send_timeout_s: float = 30.0):
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass                      # not TCP (socketpair in tests)
        self._sock = sock
        self._send_timeout_s = float(send_timeout_s)
        self._buf = bytearray()
        self._eof = False
        self._reset: Optional[OSError] = None
        self._closed = False
        try:
            name = sock.getpeername()
            self.peer = (f"{name[0]}:{name[1]}"
                         if isinstance(name, tuple) and len(name) >= 2
                         else (str(name) or "socket"))
        except OSError:
            self.peer = "socket"
        # filled by the listener handshake: the worker's HELLO payload
        # (remote pid/host — observability, never trusted for liveness)
        self.hello: dict = {}

    # -- receive ------------------------------------------------------------

    def _fill(self) -> None:
        """Drain whatever the socket has RIGHT NOW into the buffer —
        never blocks. EOF and resets are recorded, not raised: they
        surface from ``recv_bytes`` where the partial-frame context
        (torn vs clean) is known."""
        if self._eof or self._closed:
            return
        while True:
            try:
                chunk = self._sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self._eof = True
                self._reset = e
                return
            if not chunk:
                self._eof = True
                return
            self._buf += chunk

    def _ready(self) -> bool:
        """A complete frame is buffered, or an error is ready to raise."""
        if len(self._buf) >= _LEN.size:
            (n,) = _LEN.unpack_from(self._buf)
            if n > MAX_FRAME_BYTES:
                return True           # recv_bytes raises the IPCError
            if len(self._buf) >= _LEN.size + n:
                return True
        return self._eof

    def poll(self, timeout: float = 0.0) -> bool:
        """True when ``recv_bytes`` will return a frame or raise —
        never blocks past ``timeout``. The short-read loop lives here:
        however the network fragments the stream, bytes accumulate in
        the buffer until a whole length-prefixed frame is in."""
        if self._closed:
            return False
        if self._ready():
            return True
        self._fill()
        if self._ready():
            return True
        if timeout > 0 and not self._eof:
            try:
                r, _, _ = select.select([self._sock], [], [], timeout)
            except (OSError, ValueError):
                return True           # fd died: recv_bytes surfaces it
            if r:
                self._fill()
        return self._ready()

    def recv_bytes(self) -> bytes:
        if self._closed:
            raise EOFError("transport closed locally")
        if not self._ready():
            self._fill()
        if len(self._buf) >= _LEN.size:
            (n,) = _LEN.unpack_from(self._buf)
            if n > MAX_FRAME_BYTES:
                raise IPCError(
                    f"declared frame length {n} exceeds the "
                    f"{MAX_FRAME_BYTES}-byte cap (corrupt stream)")
            if len(self._buf) >= _LEN.size + n:
                frame = bytes(self._buf[_LEN.size:_LEN.size + n])
                del self._buf[:_LEN.size + n]
                return frame
        if self._eof:
            if self._buf:
                # the torn-frame / mid-frame-EOF signal: the peer died
                # (or was reset) between two writes of one frame
                how = (f"connection reset ({self._reset!r})"
                       if self._reset is not None else "peer closed")
                raise IPCError(
                    f"mid-frame EOF: {how} with {len(self._buf)} bytes "
                    f"of a partial frame buffered")
            if self._reset is not None:
                raise ConnectionResetError(str(self._reset))
            raise EOFError("peer closed the connection")
        raise BlockingIOError("no complete frame buffered (poll first)")

    # -- send ---------------------------------------------------------------

    def send_bytes(self, data: bytes) -> None:
        self._send_all(_LEN.pack(len(data)) + data)

    def send_partial_frame(self, frame: bytes, upto: int) -> None:
        """Fault-injection only: write the length prefix declaring the
        FULL frame, then just the first ``upto`` bytes of it — the
        deterministic torn frame the receive path must refuse with a
        typed error instead of waiting out or mis-parsing."""
        self._send_all((_LEN.pack(len(frame)) + frame)[:_LEN.size + upto])

    def _send_all(self, payload: bytes) -> None:
        if self._closed:
            raise BrokenPipeError("transport closed locally")
        view = memoryview(payload)
        off = 0
        deadline = time.perf_counter() + self._send_timeout_s
        while off < len(payload):
            try:
                off += self._sock.send(view[off:])
                continue
            except (BlockingIOError, InterruptedError):
                pass
            left = deadline - time.perf_counter()
            if left <= 0:
                # a peer that stopped reading: to the sender this is a
                # dead parent / dead worker, not a wait-forever
                raise BrokenPipeError(
                    f"send stalled > {self._send_timeout_s:g}s "
                    f"(peer not reading)")
            try:
                select.select([], [self._sock], [], min(left, 0.5))
            except (OSError, ValueError) as e:
                raise BrokenPipeError(f"socket died mid-send: {e!r}")

    # -- lifecycle / observability ------------------------------------------

    def set_send_timeout(self, s: float) -> None:
        """Re-bound how long a send may block. The parent sets this
        SHORT after adopting a worker's transport: its control thread
        supervises every replica, and one stalled peer must cost a
        failed send (recorded, fenced by supervision) rather than
        stalling everyone else's heartbeat deadlines. The handshake
        keeps the long default — the spec blob is large and its send
        runs on a dedicated thread."""
        self._send_timeout_s = float(s)

    def alive(self) -> bool:
        return not self._closed and not self._eof

    def state_desc(self) -> str:
        if self._closed:
            return "closed"
        if self._reset is not None:
            return "connection reset"
        if self._eof:
            return "connection closed by peer"
        return "open"

    def reset_hard(self) -> None:
        """Abort with an RST instead of a FIN (SO_LINGER 0) — the fault
        catalog's deterministic stand-in for a network-level reset."""
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0))
        except OSError:
            pass
        self.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# handshake (worker dials the parent)
# ---------------------------------------------------------------------------


def _recv_frame_deadline(transport, timeout_s: float) -> bytes:
    """One frame with a hard deadline — handshake-only (the steady-state
    protocol never blocks on a single peer)."""
    deadline = time.perf_counter() + timeout_s
    while True:
        left = deadline - time.perf_counter()
        if left <= 0:
            raise IPCError(f"handshake timed out after {timeout_s:g}s")
        if transport.poll(min(left, 0.25)):
            return transport.recv_bytes()


def parse_endpoint(endpoint: str) -> Tuple[str, int]:
    """``"host:port"`` -> ``(host, port)``; bare ``":port"`` binds all
    interfaces (remote workers must be able to reach it)."""
    host, sep, port = endpoint.rpartition(":")
    if not sep:
        raise ValueError(f"endpoint must be HOST:PORT, got {endpoint!r}")
    return host or "0.0.0.0", int(port)


def dial_parent(host: str, port: int, token: str, index: int, *,
                timeout_s: float = 60.0):
    """Worker side of the attach handshake: connect, HELLO (token +
    protocol version + claimed index), await HELLO_OK, then receive the
    pickled worker spec over the now-authenticated stream. Returns
    ``(transport, spec)``; raises ``IPCError`` on any rejection (the
    parent answers a bad HELLO by closing, which lands here as EOF)."""
    from dalle_pytorch_tpu.serve import ipc

    sock = socket.create_connection((host, port), timeout=timeout_s)
    transport = SocketTransport(sock)
    transport.send_bytes(ipc.encode_frame(ipc.HELLO, {
        "token": token, "version": ipc.PROTOCOL_VERSION,
        "index": int(index), "pid": os.getpid(),
        "host": socket.gethostname()}, seq=0))
    try:
        kind, payload, seq = ipc.decode_frame(
            _recv_frame_deadline(transport, timeout_s))
        if kind != ipc.HELLO_OK or seq != 0:
            raise IPCError(f"expected HELLO_OK/0, got {kind}/{seq}")
        spec = pickle.loads(_recv_frame_deadline(transport, timeout_s))
    except (EOFError, ConnectionResetError, OSError):
        # a parent that closes anywhere in the handshake — before
        # HELLO_OK or mid-spec — is a rejection to this worker either
        # way: one typed error, one exit code
        transport.close()
        raise IPCError(
            "parent closed during handshake (bad token, wrong index, "
            "or version skew)") from None
    except IPCError:
        transport.close()
        raise
    return transport, spec


class WorkerListener:
    """The parent's dial-in endpoint: one listening socket shared by
    every socket-transport replica. Workers connect and HELLO; the
    accept loop (one thread; one short-lived thread per handshake, so a
    dialer that connects and says nothing — the stalled-socket fault —
    times out alone instead of blocking other attaches) authenticates
    the token, checks the protocol version, matches the claimed index
    against the expected registry, ships the spec, and parks the
    attached transport for ``ChildEngineClient`` to adopt on its next
    pump. Everything unexpected is closed and counted (``rejected``),
    never attached."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 token: Optional[str] = None,
                 handshake_timeout_s: float = 10.0,
                 on_event: Optional[Callable[[dict], None]] = None):
        self.token = token or secrets.token_hex(16)
        self._handshake_timeout_s = float(handshake_timeout_s)
        self._on_event = on_event
        self._sock = socket.create_server((host, port), backlog=16)
        name = self._sock.getsockname()
        self.host, self.port = name[0], int(name[1])
        self.endpoint = f"{self.host}:{self.port}"
        # a bind address is not a destination: what a LOCAL spawn
        # dials, and what a REMOTE worker is told to dial (an
        # all-interfaces bind advertises this host's name — bind a
        # concrete address instead if that name doesn't resolve from
        # the worker hosts)
        self.dial_host = "127.0.0.1" if self.host == "0.0.0.0" \
            else self.host
        self.advertise_endpoint = (
            f"{socket.gethostname()}:{self.port}"
            if self.host == "0.0.0.0" else self.endpoint)
        self._lock = threading.Lock()
        self._expected: Dict[int, bytes] = {}       # index -> spec blob
        self._attached: Dict[int, SocketTransport] = {}
        self.rejected = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="serve-worker-listener")
        self._thread.start()

    # -- registry (called by ChildEngineClient) -----------------------------

    def expect(self, index: int, spec_blob: bytes) -> None:
        """Declare that a worker for replica ``index`` may dial in, and
        what spec to hand it. Re-registering replaces (a replaced
        replica's stale expectation must not admit a stale worker),
        and any un-taken stale transport is closed — its worker EOFs
        and exits rather than idling attached to nothing."""
        with self._lock:
            self._expected[int(index)] = spec_blob
            stale = self._attached.pop(int(index), None)
        if stale is not None:
            stale.close()

    def cancel(self, index: int) -> None:
        with self._lock:
            self._expected.pop(int(index), None)
            t = self._attached.pop(int(index), None)
        if t is not None:
            t.close()

    def take(self, index: int) -> Optional[SocketTransport]:
        """The attached transport for ``index``, if a worker completed
        the handshake since the last call. Single consumer per index."""
        with self._lock:
            return self._attached.pop(int(index), None)

    def expected_indices(self) -> list:
        """Replica indices a worker may dial in as RIGHT NOW — the
        operator's 'which --index do I start' surface (/stats carries
        it). Nothing about the registry is startup-static: a replica
        born from ``add_replica`` registers its expectation through
        the same ``expect`` call as a boot-time slot, and a retired
        replica's ``cancel`` removes its entry for good — so a fleet
        reshaped at runtime always advertises exactly the slots that
        can still accept a worker."""
        with self._lock:
            return sorted(self._expected)

    # -- accept / handshake -------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event({"kind": kind, **fields})
            except Exception:   # noqa: BLE001 — observability only
                pass

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return              # listener closed
            threading.Thread(
                target=self._handshake, args=(conn, addr), daemon=True,
                name="serve-worker-handshake").start()

    def _handshake(self, conn: socket.socket, addr) -> None:
        from dalle_pytorch_tpu.serve import ipc

        transport = SocketTransport(conn)
        peer = transport.peer
        try:
            kind, payload, seq = ipc.decode_frame(_recv_frame_deadline(
                transport, self._handshake_timeout_s))
            if kind != ipc.HELLO or seq != 0:
                raise IPCError(f"first frame must be HELLO/0, "
                               f"got {kind}/{seq}")
            token = payload.get("token")
            index = payload.get("index")
            if not auth.check_token(token, self.token):
                raise IPCError("HELLO rejected: bad token")
            if not isinstance(index, int):
                raise IPCError("HELLO rejected: no index")
        except (IPCError, EOFError, ConnectionResetError,
                OSError) as e:
            self.rejected += 1
            self._event("serve_attach_rejected", peer=peer,
                        error=repr(e))
            transport.close()
            return
        with self._lock:
            spec_blob = self._expected.get(index)
            if spec_blob is None or index in self._attached:
                self.rejected += 1
                self._event("serve_attach_rejected", peer=peer,
                            error=f"unexpected replica index {index}")
                transport.close()
                return
        try:
            transport.send_bytes(ipc.encode_frame(
                ipc.HELLO_OK, {"index": index}, seq=0))
            transport.send_bytes(spec_blob)
        except OSError as e:
            self.rejected += 1
            self._event("serve_attach_rejected", peer=peer,
                        error=f"spec hand-off failed: {e!r}")
            transport.close()
            return
        transport.hello = {k: payload.get(k) for k in ("pid", "host")}
        with self._lock:
            # attach exactly once, and only while the expectation this
            # dialer was served under is STILL current: the lock was
            # released for the spec hand-off, and in that window the
            # replica may have been fenced and re-registered (new spec)
            # or another dialer may have won — either way this worker
            # holds a stale spec and must not consume the fresh
            # expectation. Identity compare works because expect()
            # stores a new bytes object per registration.
            if index in self._attached \
                    or self._expected.get(index) is not spec_blob:
                self.rejected += 1
                stale = True
            else:
                self._expected.pop(index)
                self._attached[index] = transport
                stale = False
        if stale:
            self._event("serve_attach_rejected", peer=peer,
                        error=f"lost the attach race for replica "
                              f"{index} (stale or duplicate dialer)")
            transport.close()
            return
        self._event("serve_worker_attached", peer=peer, index=index,
                    pid=payload.get("pid"), host=payload.get("host"))

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            attached = list(self._attached.values())
            self._attached.clear()
            self._expected.clear()
        for t in attached:
            t.close()
