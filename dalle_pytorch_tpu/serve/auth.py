"""Shared token verification for every authenticated surface.

Three surfaces authenticate callers — the server's admin endpoints
(/admin/scale, /admin/profile), the transport HELLO handshake, and the
gateway's per-tenant API keys — and each used to hand-roll the same
``hmac.compare_digest`` dance. One helper means one place where the
rules live: constant-time comparison (no timing oracle on key bytes),
strings only (a list smuggled out of JSON must not reach the digest
compare), and an EMPTY expected token always refuses (an operator who
never configured a secret has not thereby configured the empty one).

Module is jax-free and import-light on purpose: both the transport
child process and the gateway import it before any accelerator code.
"""

from __future__ import annotations

import hmac
from typing import Mapping, Optional


def check_token(provided, expected) -> bool:
    """Constant-time token check. False for non-strings and for an
    empty ``expected`` — absence of a configured secret is a refusal,
    never a wildcard."""
    if not isinstance(provided, str) or not isinstance(expected, str):
        return False
    if not expected:
        return False
    return hmac.compare_digest(provided, expected)


def http_token(headers: Mapping[str, str],
               fallback_header: str = "X-Admin-Token") -> str:
    """Extract the caller's token from HTTP headers: ``Authorization:
    Bearer <token>`` wins, else the fallback header (``X-Admin-Token``
    for admin surfaces, ``X-API-Key`` for gateway tenants). Returns
    ``""`` when neither is present — which ``check_token`` refuses."""
    auth = headers.get("Authorization", "") or ""
    if auth.startswith("Bearer "):
        return auth[7:]
    return headers.get(fallback_header) or ""


def check_http(headers: Mapping[str, str], expected: str,
               fallback_header: str = "X-Admin-Token") -> bool:
    """The composed form every HTTP handler wants: pull the token out
    of ``headers``, compare against ``expected``."""
    return check_token(http_token(headers, fallback_header), expected)
