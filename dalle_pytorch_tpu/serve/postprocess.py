"""Pipelined post-decode stage: VAE image decode + optional CLIP scoring.

The engine's decode loop is sequential-latency bound (one small matmul
chain per token); the VAE deconvolution stack that turns a finished
slot's tokens into pixels is a comparatively fat one-shot program. Running
it inline would stall every OTHER slot in the batch for the duration, so
completed sequences are handed to this stage's worker thread instead —
image decoding overlaps token decoding, and the engine's fixed-shape step
never waits on pixels.

One jitted program per stage (batch-1 VAE decode, batch-1 CLIP score),
compiled on the first completion and reused — the pipeline adds no
per-request compiles. The worker fulfils each request's handle with the
final ``Result`` (tokens + image [+ clip_score]); a postprocess failure
fulfils the handle with ``status='error'`` instead of dropping it (the
no-hangs contract extends through the pipeline)."""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np

from dalle_pytorch_tpu.serve import scheduler as S


class PostProcessor:
    """Worker-thread stage between the engine and the caller.

    ``submit`` is the engine's ``complete`` hook; ``close`` drains the
    in-flight queue before returning so no handle is left unfulfilled."""

    def __init__(self, params: dict, vae_params: dict, cfg, *,
                 clip_params: Optional[dict] = None, clip_cfg=None,
                 metrics=None, max_pending: int = 64,
                 on_fulfill=None):
        import jax

        from dalle_pytorch_tpu.models import vae as vae_mod

        self.params = params
        self.vae_params = vae_params
        self.cfg = cfg
        self.clip_params = clip_params
        self.clip_cfg = clip_cfg
        self.metrics = metrics
        # called with the final Result just before handle.fulfill — the
        # server records its p50/p95 latency here so percentiles include
        # the VAE/CLIP time the caller actually waited for (before the
        # fulfill, so a caller woken by result() never reads stats that
        # predate its own completion)
        self.on_fulfill = on_fulfill
        self.decoded = 0
        # progressive previews (serve/stream.py): frames decoded from a
        # zero-padded image-token prefix and pushed into the request's
        # sink. preview_frames counts frames DELIVERED (including the
        # final full-prefix frame); preview_drops counts requests shed
        # because the pipeline queue was full — previews are strictly
        # best-effort and must never backpressure the engine thread,
        # unlike completions, which may.
        self.preview_frames = 0
        self.preview_drops = 0

        # bounded: a stalled consumer backpressures the engine thread at
        # submit() instead of growing an unbounded token backlog
        self._q: queue.Queue = queue.Queue(maxsize=max_pending)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        @jax.jit
        def _decode(vp, codebook, img_seq):
            # DALLE owns the tied codebook copy (models/dalle.py docstring)
            return vae_mod.decode(vp, img_seq, codebook=codebook)

        self._decode = _decode
        self._score = None
        if clip_params is not None:
            from dalle_pytorch_tpu.models import clip as clip_mod

            @jax.jit
            def _score(cp, text, images):
                return clip_mod.clip_apply(cp, text, images, cfg=clip_cfg)

            self._score = _score

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "PostProcessor":
        self._thread = threading.Thread(target=self._work, daemon=True,
                                        name="serve-postprocess")
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    # -- the engine's completion hook ---------------------------------------

    def submit(self, handle: S.RequestHandle, result: S.Result) -> None:
        self._q.put(("result", handle, result))

    def submit_preview(self, handle: S.RequestHandle, prefix) -> None:
        """The engine's ``on_preview`` hook (called from the harvest
        path every ``preview_every`` chunks): decode the image-token
        prefix into a progressive frame. Non-blocking — a busy pipeline
        drops the frame rather than stalling the engine; the stream
        still ends with the final frame, which rides the completion."""
        try:
            self._q.put_nowait(("preview", handle, prefix))
        except queue.Full:
            self.preview_drops += 1

    def _img_batch(self, tokens) -> np.ndarray:
        """One [1, image_seq_len] int32 row, zero-padded past the given
        tokens. EVERY decode — full result, short-grid override result,
        mid-stream preview prefix — goes through this same fixed shape,
        so the jitted VAE program compiles once and a preview's final
        full-prefix frame is bit-identical to the completion's image."""
        n = int(self.cfg.image_seq_len)
        row = np.zeros((1, n), np.int32)
        t = np.asarray(tokens, np.int32).reshape(-1)[:n]
        row[0, :len(t)] = t
        return row

    def pending(self) -> int:
        return self._q.qsize()

    def _trace_span(self, handle: S.RequestHandle,
                    error: bool = False) -> None:
        """Close the request's timeline over this stage: the
        ``postprocess`` span tiles from the engine's last harvest (or,
        process mode, the parent's result-absorb instant) to here —
        the VAE/CLIP milliseconds the caller actually waited for."""
        tr = getattr(handle, "trace", None)
        if tr is not None:
            meta = {"clip": self._score is not None}
            if error:
                meta["error"] = True
            tr.span("postprocess", time.perf_counter(), **meta)

    def _fulfill(self, handle: S.RequestHandle, result: S.Result) -> None:
        tr = getattr(handle, "trace", None)
        if tr is not None and result.trace is None:
            # summarize BEFORE the stats hook runs: _record_latency
            # reads result.trace for the prefill span (handle.fulfill
            # would attach the same summary, but only after the hook)
            result.trace = tr.summary()
        if self.on_fulfill is not None:
            try:
                self.on_fulfill(result)
            except Exception:   # noqa: BLE001 — a stats hook must never
                pass            # block the handle from being fulfilled
        handle.fulfill(result)

    # -- worker -------------------------------------------------------------

    def _preview(self, handle: S.RequestHandle, prefix) -> None:
        """Decode one progressive frame and push it into the request's
        sink. A terminal handle (cancelled mid-stream, already
        fulfilled) skips the decode — the sink is closed anyway."""
        import jax.numpy as jnp
        sink = getattr(handle, "sink", None)
        if sink is None or handle.done():
            return
        img_seq = jnp.asarray(self._img_batch(prefix))
        image = self._decode(self.vae_params,
                             self.params["image_emb"]["w"], img_seq)
        sink.push_preview(int(np.asarray(prefix).size),
                          np.asarray(image)[0])
        self.preview_frames += 1

    def _work(self) -> None:
        import jax.numpy as jnp
        while not (self._stop.is_set() and self._q.empty()):
            try:
                kind, handle, result = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if kind == "preview":
                try:
                    self._preview(handle, result)   # result = prefix
                except Exception:   # noqa: BLE001 — previews are
                    pass            # best-effort, never a terminal path
                continue
            t0 = time.perf_counter()
            try:
                img_seq = jnp.asarray(self._img_batch(result.tokens))
                image = self._decode(self.vae_params,
                                     self.params["image_emb"]["w"], img_seq)
                result.image = np.asarray(image)[0]
                if self._score is not None:
                    # score the COMPLETED text span the engine harvested
                    # (prompt + model-sampled text tokens) — exactly the
                    # full[:, :text_seq_len] row generate_images' rerank
                    # scores, so short prompts score identically to the
                    # one-shot path. Raw codes are the fallback for
                    # results built without an engine.
                    if result.text_tokens is not None:
                        text = np.asarray(result.text_tokens,
                                          np.int32)[None]
                    else:
                        req = handle.request
                        text = np.zeros((1, self.clip_cfg.text_seq_len),
                                        np.int32)
                        codes = list(req.codes)[:self.clip_cfg.text_seq_len]
                        text[0, :len(codes)] = codes
                    score = self._score(self.clip_params,
                                        jnp.asarray(text), image)
                    result.clip_score = float(np.asarray(score)[0])
                self.decoded += 1
                sink = getattr(handle, "sink", None)
                if sink is not None:
                    # the stream's closing frame IS the result image —
                    # same padded row, same jitted program as every
                    # preview, so "final SSE frame == non-streamed
                    # image" holds byte-for-byte by construction
                    sink.push_preview(int(len(result.tokens)),
                                      result.image, final=True)
                    self.preview_frames += 1
                result.total_s = round(
                    result.total_s + (time.perf_counter() - t0), 6)
                self._trace_span(handle)
                self._fulfill(handle, result)
            except Exception as e:      # noqa: BLE001 — no-hangs contract
                result = S.Result(
                    status=S.ERROR, request_id=result.request_id,
                    tokens=result.tokens, reason=f"postprocess: {e}",
                    weights_version=result.weights_version,
                    queued_s=result.queued_s, decode_s=result.decode_s,
                    total_s=round(result.total_s
                                  + (time.perf_counter() - t0), 6))
                self._trace_span(handle, error=True)
                self._fulfill(handle, result)
                if self.metrics is not None:
                    self.metrics.event(**S.structured_event(
                        "serve_postprocess_error",
                        request_id=result.request_id, error=result.reason))
