"""Typed IPC for process-isolated replica serving.

``serve/replica.py``'s fence/reclaim/replay protocol was built process-
shape-agnostic; this module is the process shape. One replica = one
child process (``serve/worker.py``) running its own Python interpreter,
its own jax client, its own ``Engine`` — so a segfault in XLA, a host
OOM kill, or an operator ``kill -9`` takes down ONE replica, not the
set. Parent and child share nothing but a transport
(``serve/transport.py``: a duplex pipe, or a dial-back TCP socket for
host-per-engine isolation and remote attach) carrying framed,
versioned, sequence-numbered, checksummed messages:

  parent -> child:  ADMIT (request batches), FENCE, SHUTDOWN, STATS_REQ,
                    MIGRATE_OUT (export one request's slot snapshot),
                    MIGRATE_IN (install a snapshot exported elsewhere)
  child -> parent:  READY, HEARTBEAT, HARVEST (completed-result batches
                    + the engine-state snapshot), STATS, CRASH, BYE,
                    MIGRATE_OUT (the export reply: snapshot or typed
                    refusal), MIGRATE_ACK (the import verdict)

Design rules, each load-bearing for the zero-loss contract:

  * **The parent never trusts the child.** Every handle routed to a
    child stays in the parent-side *shadow* (``ChildEngineClient
    .shadow``) until its result frame lands. Reclaim-on-death reads the
    shadow, never asks the corpse — a SIGKILLed child answers nothing.
  * **Counters ride the frames that explain them.** A harvest frame
    carries the child's lifetime counters and per-request progress AS
    OF that frame, and completions are never counted ahead of the
    frame that ships their result. Whatever prefix of frames the
    parent managed to read before the child died is therefore a
    CONSISTENT state: salvaged results fulfil their handles, everything
    still open is reclaimed, and the retire math (counters minus
    reclaimed requests' progress) keeps the set's aggregates counting
    distinct delivered tokens — exactly through a `kill -9`.
  * **Corruption fences, never hangs.** Every frame is
    magic+version+kind+CRC32-checked before its payload is parsed; a
    truncated or garbage frame raises a typed ``IPCError``, the client
    marks itself poisoned, and the supervisor fences the replica (kill
    + reclaim + replay) — the one safe response to a peer whose stream
    can no longer be believed.
  * **Delivery order is verified, not assumed.** Every frame carries a
    per-connection sequence number; a gap (lost frame) or a duplicate/
    reordered delivery raises ``IPCError`` and fences the replica. A
    pipe cannot reorder, but the zero-loss replay contract must not
    depend on that accident of transport: a lossy or re-delivering
    path (a proxy, a broken relay, a resumed stream) is caught at the
    protocol layer, where fencing is the defined response — counters
    and results can never be silently double-absorbed or skipped.
  * **Two clocks never cross the pipe raw.** Deadlines ship as
    remaining budget; latency is restamped against the parent's clock
    at fulfilment. The only cross-process timestamps are the snapshot
    stamps used for the IPC-lag metric, taken from ``perf_counter`` —
    CLOCK_MONOTONIC on Linux, one epoch machine-wide.

The client is SINGLE-OWNER by design: only the replica set's control
thread (threaded mode) or the sync driver (tests/bench) may touch
``route``/``pump``/``fence``/``reclaim`` — the same no-reentrancy
discipline as ``Engine.step_once``, which is what lets the whole
protocol run lock-free in the parent.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import pickle
import signal
import struct
import subprocess
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from dalle_pytorch_tpu.obs import flight as oflight
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import transport as T
from dalle_pytorch_tpu.serve.engine import COUNTERS
from dalle_pytorch_tpu.serve.transport import IPCError  # noqa: F401
#                       (re-export: the typed error every layer fences on)

# v2: the header grew a per-connection frame sequence number, and the
# handshake kinds (HELLO/HELLO_OK) joined for socket-transport attach.
# The header version pins the FRAME LAYOUT only; payload schema evolves
# by field tolerance instead (Request/Result.from_wire `.get` defaults
# — e.g. the streaming/fan-out fields stream/n_samples/
# image_seq_len_override decode from a pre-streaming peer's frames as
# their defaults), so a rolling upgrade can mix peers without a flag
# day. Bump this ONLY when the header itself changes shape.
PROTOCOL_VERSION = 2

# frame kinds — parent -> child
ADMIT = "admit"
FENCE = "fence"
SHUTDOWN = "shutdown"
STATS_REQ = "stats_req"
# frame kinds — child -> parent
READY = "ready"
HEARTBEAT = "heartbeat"
HARVEST = "harvest"
STATS = "stats"
CRASH = "crash"
BYE = "bye"
# handshake (socket transport only; see transport.WorkerListener)
HELLO = "hello"
HELLO_OK = "hello_ok"
# live migration (serve/engine.py export_slot/import_slot): MIGRATE_OUT
# is bidirectional — the parent's export request and the child's reply
# (snapshot or typed refusal); MIGRATE_IN ships a snapshot to a target
# child, answered by MIGRATE_ACK. Appended AFTER the v2 kinds so every
# existing frame keeps its positional id on the wire.
MIGRATE_OUT = "migrate_out"
MIGRATE_IN = "migrate_in"
MIGRATE_ACK = "migrate_ack"

KINDS = (ADMIT, FENCE, SHUTDOWN, STATS_REQ,
         READY, HEARTBEAT, HARVEST, STATS, CRASH, BYE,
         HELLO, HELLO_OK,
         MIGRATE_OUT, MIGRATE_IN, MIGRATE_ACK)
_KIND_ID = {k: i for i, k in enumerate(KINDS)}

_MAGIC = 0xD5
# magic, version, kind, pad, seq, crc32(payload)
_HEADER = struct.Struct("<BBBxII")

# results per harvest frame: keeps every frame comfortably under the
# pipe's atomic-write buffer (a frame torn across writes by a kill
# mid-send must be the rare case the CRC catches, not the common one)
HARVEST_BATCH = 8

# exit code the worker dies with when its RSS watchdog trips — the
# 128+SIGKILL convention container runtimes use for memory kills, so
# operators read it the same way in either environment
OOM_EXIT = 137

# exit code for a worker whose LOCAL checkpoint (ckpt-path attach specs,
# serve/worker.py) is missing or fails checkpoint.validate — a typed,
# operator-actionable death distinct from a crash: fix the path / rsync
# the checkpoint, the circuit breaker retries meanwhile
BAD_CKPT_EXIT = 5


def encode_frame(kind: str, payload: dict, seq: int = 0) -> bytes:
    body = json.dumps(payload, separators=(",", ":")).encode()
    return _HEADER.pack(_MAGIC, PROTOCOL_VERSION, _KIND_ID[kind],
                        seq & 0xFFFFFFFF, zlib.crc32(body)) + body


def decode_frame(data: bytes):
    """-> (kind, payload, seq). Raises ``IPCError`` on anything
    untrustworthy."""
    if len(data) < _HEADER.size:
        raise IPCError(f"truncated frame: {len(data)} bytes < "
                       f"{_HEADER.size}-byte header")
    magic, version, kind_id, seq, crc = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise IPCError(f"bad magic 0x{magic:02x}")
    if version != PROTOCOL_VERSION:
        raise IPCError(f"protocol version skew: peer speaks v{version}, "
                       f"this process v{PROTOCOL_VERSION}")
    if kind_id >= len(KINDS):
        raise IPCError(f"unknown frame kind id {kind_id}")
    body = data[_HEADER.size:]
    if zlib.crc32(body) != crc:
        raise IPCError("payload checksum mismatch (corrupt or torn frame)")
    try:
        payload = json.loads(body.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise IPCError(f"unparseable payload: {e}") from None
    if not isinstance(payload, dict):
        raise IPCError(f"payload must be an object, got "
                       f"{type(payload).__name__}")
    return KINDS[kind_id], payload, seq


def seq_check(got: int, expected: int) -> int:
    """Verify one received frame's sequence number; returns the next
    expected. A mismatch is a transport that lost, duplicated, or
    reordered delivery — typed ``IPCError``, and the peer is fenced:
    replay correctness cannot survive a stream whose order or
    exactly-once delivery is broken. The wire field is u32; the
    comparison masks so a counter past 2^32 doesn't false-fence."""
    if got != (expected & 0xFFFFFFFF):
        how = ("duplicate or reordered delivery"
               if got < (expected & 0xFFFFFFFF)
               else "gap: lost frame(s)")
        raise IPCError(f"frame sequence broken: got seq {got}, "
                       f"expected {expected & 0xFFFFFFFF} ({how})")
    return expected + 1


def engine_snapshot(engine, chunks: int, rss_mb: int,
                    compiling: bool) -> dict:
    """The child's engine state as one wire dict — counters, per-request
    progress, occupancy and kv facts — built by the worker and absorbed
    by ``ChildEngineClient``. Progress keys are stringified (JSON
    objects key on strings); the client converts them back."""
    snap = {
        "counters": engine.counters(),
        "progress": {str(k): int(v)
                     for k, v in engine.progress_snapshot().items()},
        "active_slots": int(engine.active_slots()),
        "queued": int(engine.queue.depth()),
        "chunks": int(chunks),
        "compiling": bool(compiling),
        "rss_mb": int(rss_mb),
        "t": time.perf_counter(),
        "pages_free": (int(engine.alloc.free)
                       if engine.kv == "paged" else -1),
        # the engine's head-of-line page reservation, if any: the oldest
        # page-deferred request's (id, pages needed). Mirrored so the
        # parent can hand the reservation back to the shared queue when
        # this replica is fenced/drained — a retiring replica must not
        # take a waiting request's page claim to the grave with it.
        "hol": (None if engine.kv != "paged"
                or getattr(engine, "_hol_rid", None) is None
                else [int(engine._hol_rid), int(engine._hol_need)]),
    }
    return snap


def _snap_fields(payload: dict):
    """Validate + convert a snapshot payload; IPCError on wrong shapes."""
    try:
        raw = payload["counters"]
        if not isinstance(raw, dict):
            raise TypeError(f"counters must be a dict, got "
                            f"{type(raw).__name__}")
        # .get: a worker built before a COUNTERS key existed (version
        # skew on a hand-started remote attach) reports 0 for it — the
        # same decode-as-default tolerance Request.from_wire gives
        # unknown request fields, instead of poisoning every heartbeat
        counters = {k: int(raw.get(k, 0)) for k in COUNTERS}
        progress = {int(k): int(v)
                    for k, v in payload["progress"].items()}
        # .get: a pre-elastic worker's snapshots carry no hol field —
        # decode as "no reservation" instead of poisoning the stream
        raw_hol = payload.get("hol")
        hol = (None if raw_hol is None
               else (int(raw_hol[0]), int(raw_hol[1])))
        return (counters, progress, int(payload["active_slots"]),
                int(payload["queued"]), int(payload["chunks"]),
                bool(payload["compiling"]), int(payload["rss_mb"]),
                float(payload["t"]), int(payload["pages_free"]), hol)
    except (KeyError, TypeError, ValueError, IndexError) as e:
        raise IPCError(f"malformed snapshot: {e!r}") from None


class ChildEngineClient:
    """Parent-side endpoint for one child-process engine replica.

    Quacks enough like ``Engine`` for the replica set's supervisor,
    router, and stats aggregation to stay mode-agnostic: the
    ``COUNTERS`` show through as attributes (mirrored from the last
    frame), plus ``num_slots`` / ``kv`` / ``active_slots()`` /
    ``last_heartbeat`` / ``compiling`` / ``fenced`` /
    ``inflight_handles()``. What it adds is the process half: PID
    liveness, exit decoding, the shadow bookkeeping, and hard-kill.

    Three LAUNCH shapes, picked by ``transport`` + ``worker_cmd``:

      * ``transport='pipe'`` (default): spawn a local child over a
        duplex pipe — PR 8's shape, unchanged;
      * ``transport='socket'``, ``worker_cmd=None``: spawn a local
        child that DIALS BACK to the parent's ``WorkerListener`` and
        receives its spec over the authenticated socket — same
        supervision, network transport;
      * ``transport='socket'``, ``worker_cmd=<template>``: launch the
        worker via an operator command (``{endpoint}``/``{index}``/
        ``{token}`` placeholders; ``{endpoint}`` is the advertised —
        dialable — address, and the token also ships via the
        ``DALLE_WORKER_TOKEN`` env var for local launchers) — e.g.
        ``ssh otherhost env DALLE_WORKER_TOKEN={token} python -m
        dalle_pytorch_tpu.serve.worker --connect {endpoint} --index
        {index}``; ``worker_cmd=''`` launches NOTHING and waits for a
        hand-started worker to dial in (remote attach). Either way the
        attached worker is supervised exactly like a spawned child:
        shadow bookkeeping, heartbeat deadline, fence→reclaim→replay.
        Without a local PID, the socket itself is the liveness signal —
        a reset or EOF on it declares the replica dead."""

    def __init__(self, params, cfg, *, index: int,
                 engine_kwargs: dict,
                 device_index: int = 0,
                 place: bool = False,
                 devices_per_replica: int = 1,
                 ckpt_path: Optional[str] = None,
                 ckpt_use_ema: bool = False,
                 ckpt_quantize: str = "none",
                 heartbeat_interval_s: float = 0.05,
                 rss_limit_mb: int = 0,
                 fault_plan: Optional[dict] = None,
                 idle_sleep_s: float = 0.002,
                 clock: Callable[[], float] = time.perf_counter,
                 on_done: Optional[Callable] = None,
                 transport: str = "pipe",
                 listener: Optional[T.WorkerListener] = None,
                 worker_cmd: Optional[str] = None):
        from dalle_pytorch_tpu.serve import worker as worker_mod

        self.clock = clock
        self.index = int(index)
        self.num_slots = int(engine_kwargs.get("num_slots", 4))
        self.chunk_steps = int(engine_kwargs.get("chunk_steps", 8))
        self.kv = str(engine_kwargs.get("kv", "dense"))
        self.on_done = on_done
        self.transport_kind = str(transport)
        if ckpt_path is None and params is None:
            raise ValueError("ChildEngineClient needs params or a "
                             "ckpt_path for the worker to load from")
        spec = {
            "index": self.index,
            # numpy pytree (picklable) — or, with ckpt_path, NOTHING:
            # the worker loads + validates the checkpoint locally
            # (serve/worker.py), and the attach spec shrinks from the
            # weight pytree to a path string
            "params": None if ckpt_path is not None else params,
            "ckpt_path": ckpt_path,
            # worker-side serving transforms for ckpt-path specs: the
            # worker applies EMA swap / int8 quantization AFTER its
            # local load (serve/worker.py load_ckpt_params), so remote
            # workers serve the same weights --use_ema/--quantize give
            # the in-process engine
            "ckpt_use_ema": bool(ckpt_use_ema),
            "ckpt_quantize": str(ckpt_quantize),
            "cfg": cfg,
            "engine_kwargs": dict(engine_kwargs),
            "device_index": int(device_index),
            "place": bool(place),
            "devices_per_replica": int(devices_per_replica),
            "heartbeat_interval_s": float(heartbeat_interval_s),
            "rss_limit_mb": int(rss_limit_mb),
            "faults": fault_plan,
            "idle_sleep_s": float(idle_sleep_s),
        }
        self._listener = listener
        self._proc = None
        self._popen = None
        self._conn = None
        self.pid: Optional[int] = None
        self.peer = ""
        self.remote_host = ""
        self.awaiting_operator = False
        if transport == "pipe":
            # spawn, not fork: the parent holds a live jax runtime, and
            # a forked copy of it is undefined behaviour — the child
            # builds its own interpreter and its own jax client from
            # scratch, which is the entire point of the isolation
            ctx = mp.get_context("spawn")
            parent_end, child_end = ctx.Pipe(duplex=True)
            self._conn = T.PipeTransport(parent_end)
            self._proc = ctx.Process(
                target=worker_mod.worker_main, args=(spec, child_end),
                daemon=True, name=f"serve-worker-{index}")
            self._proc.start()
            # the parent MUST close its copy of the child's end: the
            # child detects parent death as EOF on the pipe, which only
            # happens when no live process holds a write handle
            child_end.close()
            self.pid = self._proc.pid
            self.peer = f"pipe:pid={self.pid}"
        elif transport == "socket":
            if listener is None:
                raise ValueError("transport='socket' needs a "
                                 "WorkerListener")
            # the spec travels over the authenticated socket AFTER the
            # HELLO, so a hand-started remote worker needs nothing but
            # endpoint + token + index
            listener.expect(self.index, pickle.dumps(spec))
            if worker_cmd is None:
                ctx = mp.get_context("spawn")
                self._proc = ctx.Process(
                    target=worker_mod.worker_main_dial,
                    args=(listener.dial_host, listener.port,
                          listener.token, self.index),
                    daemon=True, name=f"serve-worker-{index}")
                self._proc.start()
                self.pid = self._proc.pid
            elif worker_cmd == "":
                # remote attach: an operator (or an external launcher)
                # starts the worker by hand; no spawn deadline applies
                self.awaiting_operator = True
            else:
                import shlex
                # {endpoint} is the ADVERTISED address (a 0.0.0.0 bind
                # is not a destination a remote host can dial); {token}
                # is for launchers that cross a host boundary — a plain
                # env var does not survive ssh (no SendEnv), so the
                # documented ssh form inlines it via `env` on the far
                # side. The env var still covers local launchers.
                cmd = worker_cmd.format(
                    endpoint=listener.advertise_endpoint,
                    index=self.index, token=listener.token)
                env = dict(os.environ)
                env[T.TOKEN_ENV] = listener.token
                self._popen = subprocess.Popen(shlex.split(cmd), env=env)
                self.pid = self._popen.pid
        else:
            raise ValueError(f"unknown transport {transport!r}")
        self.started_t = self.clock()
        # per-connection frame sequencing: over a socket, seq 0 of each
        # direction was spent on HELLO/HELLO_OK during the handshake
        self._tx_seq = 1 if transport == "socket" else 0
        self._rx_seq = 1 if transport == "socket" else 0

        # lifecycle flags (single-owner: control thread / sync driver)
        self.ready = False
        self.fenced = False
        self.crashed = False            # child shipped a CRASH frame
        self.poisoned = False           # protocol error: fence me
        self.bye = False                # clean goodbye received
        self.last_error = ""
        self.worker_weights_version = ""    # READY announcement

        # the shadow: every handle routed here and not yet resolved —
        # the reclaim surface, owned and trusted by the parent only
        self.shadow: Dict[int, S.RequestHandle] = {}

        # parent-side MIRROR of the child engine's flight recorder:
        # heartbeat/harvest frames carry the child ring's increments,
        # so the last-N events of a SIGKILLed child survive here — the
        # fence dump reads this mirror, never asks the corpse
        self.flight = oflight.FlightRecorder(capacity=512)

        # last-frame mirror of the child engine's state
        self.counter_state = {k: 0 for k in COUNTERS}
        self.progress: Dict[int, int] = {}
        self.active = 0
        self.queued = 0
        self.chunks = 0
        self.compiling = True           # bring-up IS a compile phase
        self.rss_mb = 0
        self.pages_free = -1
        self.hol = None                 # (rid, need) per the last frame
        self.last_heartbeat = self.clock()
        self.last_frame_t = self.clock()    # ANY decoded frame stamps it
        self.stats_reply: Optional[dict] = None
        # the child's parked answer to the ONE in-flight migration
        # (export reply or import ack) — single-owner control thread,
        # migrations run serially, so one slot suffices
        self.migrate_reply: Optional[dict] = None
        # child-stamp -> parent-absorb lag per frame (the isolation tax
        # bench_serve's --isolation leg reports); perf_counter is
        # CLOCK_MONOTONIC on Linux — one epoch across processes
        self.ipc_lag_s: deque = deque(maxlen=10_000)

    def __getattr__(self, name):
        # the COUNTERS surface (tokens_decoded, decode_traces, ...)
        # mirrors the last frame — this is what lets the replica set's
        # _agg()/stats() read a client exactly like an Engine
        counters = self.__dict__.get("counter_state")
        if counters is not None and name in counters:
            return counters[name]
        raise AttributeError(name)

    # -- transport adoption (socket dial-back) ------------------------------

    def _maybe_attach(self) -> None:
        """Adopt the transport a dialing worker completed the HELLO
        handshake on (socket mode; the listener parks it under this
        replica's index). Spawned-socket children, launcher-started
        workers, and hand-started remote workers all arrive here."""
        if self._conn is not None or self._listener is None:
            return
        t = self._listener.take(self.index)
        if t is None:
            return
        # short send bound from here on: this transport is now driven
        # by the control thread that supervises EVERY replica, and one
        # worker that stops reading must cost a recorded send failure
        # (fence + replay), never stall the others' heartbeat deadlines
        t.set_send_timeout(2.0)
        self._conn = t
        self.peer = t.peer
        hello = t.hello or {}
        if self.pid is None:
            # a remote worker's pid: triage info for /healthz, never a
            # liveness signal — the socket is the liveness signal
            pid = hello.get("pid")
            self.pid = int(pid) if isinstance(pid, int) else None
        self.remote_host = str(hello.get("host") or "")
        if self.awaiting_operator:
            self.awaiting_operator = False
            # the wait for an operator was open-ended; supervision
            # deadlines (attach -> READY) start now
            self.started_t = self.clock()

    # -- sending ------------------------------------------------------------

    def _send(self, kind: str, payload: dict) -> bool:
        self._maybe_attach()
        if self._conn is None:
            if not self.last_error:
                self.last_error = "no worker transport attached yet"
            return False
        try:
            self._conn.send_bytes(encode_frame(kind, payload,
                                               self._tx_seq))
            self._tx_seq += 1
            return True
        except (OSError, ValueError) as e:
            if not self.last_error:
                self.last_error = f"transport write failed: {e!r}"
            # a write failure over a STILL-LIVE stream (a peer that
            # stopped reading, a send timeout) leaves routed handles
            # stranded unless someone fences: the dropped frame also
            # un-syncs our tx sequence, so this stream can never be
            # trusted again — poison, and the supervisor fences +
            # replays the shadow. When the transport itself is dead,
            # liveness (PID, or the socket state for a remote worker)
            # already tells the story and fences the same way.
            if self._conn.alive():
                self.poisoned = True
            return False

    def route(self, handles: List[S.RequestHandle]) -> None:
        """Hand requests to the child. They enter the shadow FIRST: if
        the write fails (child mid-death), the reclaim sweep still owns
        them and they replay on a survivor — routed work is never lost
        to a torn pipe."""
        now = self.clock()
        for h in handles:
            self.shadow[h.request.request_id] = h
        self._send(ADMIT, {"requests": [h.to_wire(now) for h in handles]})

    def request_stats(self) -> None:
        self._send(STATS_REQ, {})

    # -- live migration (parent side) ----------------------------------------

    def _await_migrate(self, timeout: float) -> Optional[dict]:
        """Pump until the child answers the in-flight migration frame
        (or the stream dies / the deadline passes — None). Absorbs
        every other frame kind normally while waiting, so heartbeats
        and harvests keep landing mid-transfer."""
        deadline = self.clock() + timeout
        while True:
            self.pump(0.01)
            reply, self.migrate_reply = self.migrate_reply, None
            if reply is not None:
                return reply
            if self.poisoned or self.crashed or self.fenced \
                    or not self.alive_proc() \
                    or self.clock() >= deadline:
                return None

    def export_request(self, request_id: int,
                       timeout: float = 30.0) -> dict:
        """Ask the child to export ``request_id``'s slot (MIGRATE_OUT)
        and return the snapshot payload. On success the child has
        already vacated the slot — the parent-side handle stays in THIS
        client's shadow until the caller hands it to the target. Raises
        the typed ``MigrationError`` when the child refuses, dies
        mid-transfer, or never answers (the replay-fallback signal:
        the handle is still shadow-owned, so nothing is lost)."""
        from dalle_pytorch_tpu.serve.engine import MigrationError
        if int(request_id) not in self.shadow:
            raise MigrationError(
                "not_found", f"request {request_id} is not routed here")
        if not self._send(MIGRATE_OUT, {"request_id": int(request_id)}):
            raise MigrationError(
                "source_dead",
                self.last_error or "transport write failed")
        reply = self._await_migrate(timeout)
        if reply is None:
            raise MigrationError(
                "source_dead",
                self.last_error or "source died or went silent "
                "mid-transfer")
        if not reply.get("ok"):
            raise MigrationError(str(reply.get("reason") or "transfer"),
                                 str(reply.get("error") or ""))
        snap = reply.get("snap")
        if not isinstance(snap, dict):
            raise MigrationError("transfer", "malformed export reply "
                                 "(no snapshot object)")
        return snap

    def import_request(self, snap: dict, handle: S.RequestHandle,
                       timeout: float = 30.0) -> None:
        """Ship an exported snapshot to this child (MIGRATE_IN) and
        wait for its MIGRATE_ACK. The handle enters the shadow FIRST —
        ``route``'s rule: if the child dies mid-import, the reclaim
        sweep still owns the request and it replays. A refused or
        unanswered import pops the handle back out and raises the
        typed ``MigrationError`` so the caller's fallback ladder
        (requeue-for-replay) runs."""
        from dalle_pytorch_tpu.serve.engine import MigrationError
        rid = int(snap.get("request_id", -1))
        self.shadow[rid] = handle
        sent = self._send(MIGRATE_IN, {"snap": snap})
        reply = self._await_migrate(timeout) if sent else None
        if reply is None or not reply.get("ok"):
            self.shadow.pop(rid, None)
            if reply is None:
                raise MigrationError(
                    "target_dead",
                    self.last_error or "target died or went silent "
                    "mid-import")
            raise MigrationError(str(reply.get("reason") or "transfer"),
                                 str(reply.get("error") or ""))

    # -- receiving ----------------------------------------------------------

    def pump(self, poll_s: float = 0.0) -> bool:
        """Drain and dispatch every complete frame the child has sent.
        Returns True when any frame was processed. A fenced client
        never pumps (late frames from a zombie must not fulfil
        anything); a frame that fails to decode poisons the client —
        the supervisor fences it on the next sweep."""
        if self.fenced:
            return False
        self._maybe_attach()
        if self._conn is None:
            return False
        did = False
        first = True
        while True:
            try:
                if not self._conn.poll(poll_s if first else 0):
                    break
                data = self._conn.recv_bytes()
            except IPCError as e:
                # the transport itself caught a lie: a torn frame, a
                # reset mid-frame, an oversize length — fence material
                self.poisoned = True
                self.last_error = f"protocol error: {e}"
                break
            except (EOFError, OSError):
                # clean close at a frame boundary: liveness (PID for a
                # local child, the socket state for a remote worker)
                # tells the story
                break
            first = False
            did = True
            try:
                kind, payload, seq = decode_frame(data)
                self._rx_seq = seq_check(seq, self._rx_seq)
                self.last_frame_t = self.clock()
                self._dispatch(kind, payload)
            except IPCError as e:
                self.poisoned = True
                self.last_error = f"protocol error: {e}"
                break
        return did

    def _dispatch(self, kind: str, payload: dict) -> None:
        if kind == READY:
            self.ready = True
            self.compiling = True       # first chunks still compile
            self.last_heartbeat = self.clock()
            try:
                self.rss_mb = int(payload.get("rss_mb", 0))
            except (TypeError, ValueError):
                raise IPCError(f"malformed READY: {payload!r}") from None
            # what generation the worker SAYS it serves (rolling
            # upgrades re-spawn workers on new weights; the replica
            # set verifies the attach landed on the one it asked for).
            # .get: a pre-elastic worker simply doesn't announce.
            self.worker_weights_version = \
                str(payload.get("weights_version") or "")
        elif kind in (HEARTBEAT, HARVEST):
            # flight-ring increments first (the mirror should already
            # hold the spans/events that EXPLAIN a result when it
            # lands), then results, then the snapshot that counts them
            # — absorbing in this order keeps parent state consistent
            # even if a later frame never arrives. .get + isinstance:
            # a pre-obs worker ships no events; a malformed entry is
            # advisory observability, dropped rather than fenced over.
            for ev in payload.get("events") or ():
                if isinstance(ev, dict):
                    self.flight.record(ev)
            if kind == HARVEST:
                for d in payload.get("results", ()):
                    self._absorb_result(d)
            if payload.get("snap") is not None:
                self._absorb_snapshot(payload["snap"])
            self.last_heartbeat = self.clock()
        elif kind == STATS:
            reply = payload.get("stats")
            if not isinstance(reply, dict):
                raise IPCError(f"malformed STATS: {payload!r}")
            self.stats_reply = reply
        elif kind == CRASH:
            self.crashed = True
            self.last_error = str(payload.get("error", "child crash"))
        elif kind == BYE:
            self.bye = True
        elif kind in (MIGRATE_OUT, MIGRATE_ACK):
            # the child's verdict on the in-flight export/import —
            # parked for the control thread's _await_migrate
            self.migrate_reply = payload
        else:
            raise IPCError(f"unexpected frame kind {kind!r} from child")

    def _absorb_result(self, d: dict) -> None:
        try:
            result = S.Result.from_wire(d)
        except (KeyError, TypeError, ValueError) as e:
            raise IPCError(f"malformed result: {e!r}") from None
        handle = self.shadow.pop(result.request_id, None)
        if handle is None or handle.done():
            return      # reclaimed+replayed already, or a stale echo
        # the child's span records ride the result frame: merge them
        # into the parent trace (same machine, one CLOCK_MONOTONIC
        # epoch, so they tile against the parent's route span) and
        # re-anchor the tiling pointer at the absorb instant — the
        # postprocess span starts here. Advisory: malformed spans are
        # skipped inside merge_wire, never fence material.
        if handle.trace is not None and d.get("spans"):
            handle.trace.merge_wire(d["spans"], self.clock())
        # honest caller-observed latency: restamp against the PARENT
        # clock and the caller's real submit time (the child's stamps
        # are relative to its own admission)
        result.total_s = round(self.clock() - handle.request.submit_t, 6)
        if self.on_done is not None:
            self.on_done(handle, result)
        else:
            handle.fulfill(result)

    def _absorb_snapshot(self, snap: dict) -> None:
        (self.counter_state, self.progress, self.active, self.queued,
         self.chunks, self.compiling, self.rss_mb, stamp,
         self.pages_free, self.hol) = _snap_fields(snap)
        self.ipc_lag_s.append(max(time.perf_counter() - stamp, 0.0))

    # -- supervision surface ------------------------------------------------

    def active_slots(self) -> int:
        return self.active

    def inflight_handles(self) -> List[S.RequestHandle]:
        return list(self.shadow.values())

    def alive_proc(self) -> bool:
        """The replica's liveness, by the strongest signal available.
        Over a socket, a dead CONNECTION means a dead replica whatever
        the process state — an unreachable engine cannot serve, and a
        remote worker has no PID to ask. With a local process (spawn)
        or a launcher child (Popen), PID liveness layers on top. A
        worker not yet attached counts as alive: the spawn/attach
        deadline, not this check, bounds that phase."""
        if self._conn is not None and self._conn.kind == "socket" \
                and not self._conn.alive():
            return False
        if self._proc is not None:
            return self._proc.is_alive()
        if self._popen is not None:
            if self._popen.poll() is None:
                return True
            # the launcher exited (an ssh relay dropping out): the
            # worker may still be up — believe the live socket
            return self._conn is not None and self._conn.alive()
        if self._conn is None:
            return True         # attach mode, still awaiting dial-in
        return self._conn.alive()

    @staticmethod
    def _decode_exit(code: Optional[int]) -> str:
        if code is None:
            return "running"
        if code < 0:
            try:
                name = signal.Signals(-code).name
            except ValueError:
                name = f"signal {-code}"
            return f"killed by {name}"
        if code == OOM_EXIT:
            return f"oom-killed (exit {OOM_EXIT}: child RSS limit)"
        if code == BAD_CKPT_EXIT:
            return (f"invalid checkpoint (exit {BAD_CKPT_EXIT}: the "
                    f"worker's local checkpoint failed validation)")
        return f"exit code {code}"

    def exit_desc(self) -> str:
        """Decode how the child died — the second liveness signal. A
        negative exitcode is the terminating signal (SIGKILL for a host
        OOM killer or `kill -9`, SIGSEGV for an XLA crash); exit 137 is
        the worker's own RSS watchdog (container OOM convention). A
        worker with no local process (remote attach) has only the
        connection's state to report."""
        if self._proc is not None:
            return self._decode_exit(self._proc.exitcode)
        if self._popen is not None:
            return self._decode_exit(self._popen.poll())
        if self._conn is None:
            return "no worker attached"
        return f"remote worker: {self._conn.state_desc()}"

    def transport_info(self, now: Optional[float] = None) -> dict:
        """The per-replica transport block /healthz and /stats carry:
        transport kind, peer address, and seconds since the last
        decoded frame (the staleness an operator actually triages
        with; heartbeat_age tracks only HEARTBEAT/HARVEST)."""
        now = self.clock() if now is None else now
        info = {"transport": self.transport_kind,
                "peer": self.peer or "unattached",
                "last_frame_age_s": round(
                    max(now - self.last_frame_t, 0.0), 4)}
        if self.remote_host:
            info["worker_host"] = self.remote_host
        return info

    # -- fencing / teardown -------------------------------------------------

    def fence(self) -> None:
        """One-way: after this, no frame from the child is ever
        processed again — its requests belong to the reclaim sweep.
        The transport is released too (a fenced client never reads or
        writes again; holding the fd would leak one per failover on a
        long-lived server), and any dial-in expectation this replica
        registered is cancelled so a stale worker cannot attach to a
        fenced slot. Closing the socket is also what tells a live
        remote worker its parent is gone — it EOFs and exits on its
        own (the worker's no-leak contract)."""
        self.fenced = True
        if self._listener is not None:
            try:
                self._listener.cancel(self.index)
            except Exception:   # noqa: BLE001 — teardown best-effort
                pass
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass

    def hard_kill(self, join_s: float = 5.0) -> None:
        """SIGKILL the child (idempotent; a corpse stays dead). No
        grace: by the time a replica is being fenced, its child is
        crashed, wedged, or lying — all three deserve -9. A remote
        worker has no process to signal — frames it already wrote
        remain salvageable, and the fence's transport close is what
        reaches it."""
        if self._proc is not None:
            if self._proc.is_alive():
                try:
                    self._proc.kill()
                except (OSError, ValueError):
                    pass
            self._proc.join(join_s)
        elif self._popen is not None:
            try:
                self._popen.kill()
            except OSError:
                pass
            try:
                self._popen.wait(join_s)
            except (OSError, subprocess.TimeoutExpired):
                pass

    def salvage(self) -> None:
        """After the child is down: drain every complete frame it wrote
        before dying. Results that made it into the pipe fulfil their
        handles (they will NOT be replayed); the final snapshot brings
        the counter mirror to the last consistent state. Call BEFORE
        ``fence`` — a fenced client drops frames."""
        while self.pump():
            pass

    def reclaim(self) -> List[S.RequestHandle]:
        """Every routed, still-open handle — the replay set. Clears the
        shadow; call exactly once, after ``salvage`` + ``fence``."""
        out = [h for h in self.shadow.values() if not h.done()]
        self.shadow.clear()
        return out

    def retire_counters(self,
                        reclaimed: List[S.RequestHandle]) -> Dict[str, int]:
        """The dead child's counters minus the reclaimed requests'
        harvested prefixes (per the last frame's progress map): replay
        re-credits every token, so this keeps the set's aggregates
        counting distinct delivered tokens across a hard kill."""
        out = dict(self.counter_state)
        for h in reclaimed:
            n = self.progress.get(h.request.request_id, 0)
            out["tokens_decoded"] -= n
            out["occupancy_sum"] -= n
        return out

    def close(self, timeout: float = 10.0) -> None:
        """Graceful shutdown: ask, wait, then kill. Frames written
        before the child exited are salvaged either way. A remote
        worker (nothing to join) gets the SHUTDOWN frame and a bounded
        pump for its BYE before the transport closes under it."""
        if self._proc is not None:
            # only wait for a child that actually HEARD the shutdown:
            # a socket child still dialing (no transport attached) or
            # a dead pipe would make this join burn its whole timeout
            # on a worker with no reason to exit
            if self._proc.is_alive() and self._send(SHUTDOWN, {}):
                self._proc.join(timeout)
        elif self._popen is not None:
            if self._popen.poll() is None and self._send(SHUTDOWN, {}):
                try:
                    self._popen.wait(timeout)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        elif self._conn is not None and self._conn.alive():
            self._send(SHUTDOWN, {})
            deadline = time.perf_counter() + timeout
            while not self.bye and time.perf_counter() < deadline:
                # a worker that died or lied mid-shutdown will never
                # BYE — stop waiting the moment the stream can say so
                if self.poisoned or not self._conn.alive():
                    break
                if not self.pump(0.05):
                    time.sleep(0.01)
        self.hard_kill()
        self.salvage()
        self.fence()
