"""Continuous-batching inference: slot-pool engine, admission queue,
pipelined postprocess, threaded server (docs/SERVING.md).

Import surface kept lazy-friendly: ``scheduler`` pulls no jax, so queue
types (Request/Result/QueueFull) are importable before a backend exists —
the same discipline as ``resilience`` (utils/metrics.py note)."""

from dalle_pytorch_tpu.serve.auth import (  # noqa: F401
    check_http, check_token, http_token)
from dalle_pytorch_tpu.serve.kv_pool import (  # noqa: F401
    PageAllocator, PagePoolExhausted, PageReleaseUnderflow, pages_for)
from dalle_pytorch_tpu.serve.prefix_cache import (  # noqa: F401
    PrefixEntry, PrefixIndex, content_key, prefix_key)
from dalle_pytorch_tpu.serve.scheduler import (  # noqa: F401
    CANCELLED, DEADLINE_EXCEEDED, ERROR, OK, REJECTED, InvalidRequest,
    QueueClosed, QueueFull, Request, RequestHandle, RequestQueue, Result,
    SamplingParams, ServeRejected, WeightedFairQueue, bucket_for,
    group_by_bucket, prefill_buckets)
from dalle_pytorch_tpu.serve.fanout import (  # noqa: F401
    GroupFuture, group_pages_saved, rank_samples, sample_seed,
    submit_group)
from dalle_pytorch_tpu.serve.stream import (  # noqa: F401
    TokenSink, sse_bytes, unpack_image)
from dalle_pytorch_tpu.serve.tenancy import (  # noqa: F401
    TIERS, AuthError, TenantSpec, TenantTable, TenantThrottled,
    TokenBucket)


def __getattr__(name):
    # Engine / PostProcessor / InferenceServer import jax at construction;
    # defer the module imports so `from dalle_pytorch_tpu import serve`
    # stays cheap for callers that only need the queue types.
    if name == "Engine":
        from dalle_pytorch_tpu.serve.engine import Engine
        return Engine
    if name in ("ReplicaSet", "ScaleError", "UpgradeAborted",
                "ReplayVersionMismatch"):
        from dalle_pytorch_tpu.serve import replica
        return getattr(replica, name)
    if name in ("Autoscaler", "AutoscalePolicy"):
        from dalle_pytorch_tpu.serve import autoscale
        return getattr(autoscale, name)
    if name == "MeshEngine":
        from dalle_pytorch_tpu.serve.mesh_engine import MeshEngine
        return MeshEngine
    if name == "PostProcessor":
        from dalle_pytorch_tpu.serve.postprocess import PostProcessor
        return PostProcessor
    if name in ("InferenceServer", "make_http_server", "serve_http"):
        from dalle_pytorch_tpu.serve import server
        return getattr(server, name)
    if name in ("Gateway", "Cell", "make_gateway_http_server",
                "serve_gateway_http"):
        # gateway.py itself is jax-free, but it imports the faults /
        # obs stack — defer it with the heavy modules anyway
        from dalle_pytorch_tpu.serve import gateway
        return getattr(gateway, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
