"""Slot-pool continuous-batching decode engine, device-resident.

The one-shot path (``cli/gen_dalle.py`` -> ``models.dalle.generate_images``)
pays full compile + prefill + ~1024 sequential decode steps PER REQUEST,
with no batching across requests. This engine is the serving answer: a
fixed ``[num_slots]`` decode batch compiled ONCE, where requests join and
leave via masking (the slot-based continuous batching standard on TPU —
PAPERS.md "Ragged Paged Attention", "Serving Gemma on Cloud TPU"), and —
since one host round-trip per decode step is the dominant non-compute
cost on a real chip — a steady-state loop the host is NOT in:

  * ALL per-slot decode state lives on device: ``cur_tok``, ``pos``, an
    ``active`` mask, per-slot RNG keys, temperature, top-k and top-p,
    plus the slot-pool KV cache (``ops.decode.init_cache`` at
    batch = num_slots). The host keeps only request bookkeeping
    (``_Slot``: handle, emitted-so-far, timestamps);
  * the steady-state program is ``chunk_steps`` (K) decode steps FUSED
    into one jitted ``lax.scan`` (``ops.decode.decode_loop``) that
    writes each step's emitted tokens into a device-side
    ``[num_slots, K]`` emit ring. The engine dispatches chunk programs
    back-to-back and harvests a chunk's ring with a single
    ``jax.device_get`` one chunk LATER (double-buffered: the blocking
    get on chunk N overlaps the device computing chunk N+1), so ~1024
    blocking syncs per request become ~1024/K overlapped ones;
  * a slot that emits its last token deactivates itself INSIDE the fused
    program (it keeps computing into a dead mask, parked at pos 0,
    until the harvest notices) — finished-slot detection costs no
    mid-chunk sync. Completion, and therefore the request's latency, is
    timestamped at harvest (what the caller actually observes; a request
    can wait up to K-1 dead steps plus one in-flight chunk for it —
    docs/SERVING.md "Choosing K");
  * admission pads prompts up to a small fixed set of BUCKET lengths
    (``scheduler.prefill_buckets``) and always prefills a full
    ``num_slots``-row group (unused rows scatter to a dropped
    out-of-range slot index), so prefill compiles exactly once per
    bucket for the engine's life — asserted by tests through
    ``analysis.guards.compile_count``. Padding is causal-safe: cache
    rows [0, t0) and the first sampled token depend only on positions
    < t0, and every padded garbage row [t0, bucket) is overwritten by
    the decode step for that position before any later step can attend
    to it.

Equivalence contract (tests/test_serve.py pins it): for the same params /
prompt / seed / sampling knobs, a slot's emitted image tokens are
IDENTICAL to ``generate_images`` at batch 1 — for every chunk size K —
because the fused loop reuses ``decode_token_embed`` / ``to_logits`` /
``models.dalle.sample_per_slot`` (the per-slot traced-parameter form of
the one-shot sampler's filters) with the same
``fold_in(request_rng, position)`` key discipline, and K only changes
where the host reads the stream, never what the device computes.

KV layouts (``kv=``): the default ``"dense"`` slot pool reserves
``num_slots × seq_len`` KV rows up front; ``"paged"`` replaces it with a
shared page pool + per-slot block tables (``serve/kv_pool.py``,
``ops.decode.decode_loop_paged``) so HBM residency tracks where requests
actually ARE in their sequences, not where they could end up — the same
budget sustains strictly more concurrent requests (bench_serve asserts
it). Pages are allocated at admission for the prompt span, grown ahead
of each fused chunk as ``pos`` crosses page boundaries, and freed at
completion/expiry/eviction; when the pool runs dry mid-decode the
lowest-priority active request is EVICTED back to the queue (typed
``PagePoolExhausted`` path — pages freed, request re-queued, its handle
preserved; deterministic sampling replays its exact tokens on
re-admission). The steady-state loop stays in the identical one-compile,
transfer-clean, emit-ring regime: the only paged-specific host traffic
is an explicit ``device_put`` of the tiny block table when it changes.

Cross-request prefix cache (``prefix_cache=True``, paged only): prompt
KV pages become a refcounted, copy-on-write, content-addressed resource
(``serve/prefix_cache.py`` + the refcounted ``PageAllocator``). On
admission, a prompt whose key is indexed takes the WARM path: the
entry's full prompt pages map straight into the new slot's block table
(refcount++ — zero prefill FLOPs, zero new pages for the shared span),
the partial boundary page is forked copy-on-write from the entry's
device snapshot into one private page, and the first token is sampled
from the entry's cached last hidden row by a tiny warm-admission
program (one ``to_logits`` + per-slot sample, compiled once, ever).
Sharing is read-only BY CONSTRUCTION: shared pages lie wholly below the
prompt length t0, and decode only ever appends at positions >= t0 —
asserted at every warm mapping. Slot teardown releases references;
pages return to the free list only at refcount zero, so an eviction
victim can never hand a sibling's mapped page to the next allocation.
Under page pressure the LRU end of the index is dropped BEFORE any live
request is evicted.

Per-request classifier-free guidance (``Request.cfg_scale > 0``): the
request admits a cond/uncond SLOT PAIR — the uncond member is a shadow
slot running the all-PAD null caption — and the guided logit mix
``l_u + scale * (l_c - l_u)`` is folded into the fused decode program
itself (``models.dalle.sample_per_slot``'s partner/cfg_scale/uncond
arguments), so ``decode_traces == 1`` still holds and the pair's tokens
are byte-identical to ``generate_images(guidance=scale)``. With the
prefix cache on, the pair shares every cacheable prompt span physically
(the null caption is ONE entry shared by all guided requests of a given
prompt length) and diverges copy-on-write only over the generated span
— which is what makes per-request guidance affordable: < 2x pages, not
2x everything.

Not supported per-request: padded prompt masks (requests carry unpadded
codes, gen_dalle's default mode).

The engine is deliberately single-threaded and drivable iteration-by-
iteration (``step_once`` = expire/admit/dispatch-one-chunk/harvest-one)
so tests and the bench can run it deterministically; ``serve.server``
wraps it in a thread for live traffic.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dalle_pytorch_tpu.serve import scheduler as S

# the engine's lifetime counters, as one tuple so every aggregation
# site — the replica set's retired-counter fold, the IPC heartbeat
# snapshot a child worker ships, the parent-side client's mirror —
# reads the SAME set and cannot drift from stats()
COUNTERS = ("tokens_decoded", "decode_steps", "harvests",
            "occupancy_sum", "completed", "expired",
            "decode_traces", "prefill_traces", "evicted",
            "prefix_hits", "cfg_pairs", "reaped")


class ProfileError(RuntimeError):
    """Typed rejection of a serve-side profiler capture request
    (``Engine.request_profile`` / ``POST /admin/profile``): a capture
    is already active (jax.profiler allows exactly one trace at a
    time), or the target replica cannot be profiled (a child-process
    engine's programs run in another interpreter). ``record`` is the
    structured event — the HTTP facade maps ``capture_active`` to a
    409, mirroring ``replica.ScaleError``."""

    def __init__(self, record: dict):
        super().__init__(f"{record.get('reason', 'profile rejected')}")
        self.record = record


class MigrationError(RuntimeError):
    """Typed failure of a live slot migration (export or import) — the
    signal that flips the replica set from "move the KV pages" to the
    replay fallback (requeue + deterministic re-decode from token
    zero), never a dropped request. ``reason`` is a short machine slug
    (``kv_dense``, ``not_found``, ``fenced``, ``weights_version``,
    ``page_size``, ``layout``, ``target_slots``, ``target_pages``,
    ``source_dead``, ``target_dead``, ``transfer``) the structured
    ``serve_migrate_fallback`` event carries."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"migration failed ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


def _pack_array(a) -> dict:
    """One host array as a JSON-safe dict (dtype/shape/base64 bytes) —
    the page-snapshot wire form MIGRATE frames carry. Exact: raw bytes,
    no float text round-trip."""
    import base64
    a = np.ascontiguousarray(a)
    name = a.dtype.str
    if a.dtype.kind == "V":
        # ml_dtypes extension types (bfloat16 pools): numpy's .str is
        # an opaque void tag ("|V2") the importer could not rebuild —
        # ship the real name instead
        name = a.dtype.name
    return {"dtype": name, "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _unpack_array(d: dict) -> np.ndarray:
    import base64
    raw = base64.b64decode(d["data"])
    try:
        dtype = np.dtype(d["dtype"])
    except TypeError:
        import ml_dtypes
        dtype = np.dtype(getattr(ml_dtypes, d["dtype"]))
    return np.frombuffer(raw, dtype=dtype).reshape(
        [int(s) for s in d["shape"]])


class _Slot:
    """Host-side bookkeeping for one slot of the pool. Decode state
    (position, current token) lives on device; the host only accumulates
    harvested tokens against the handle.

    A classifier-free-guidance pair is two slots: the cond slot carries
    ``pair`` (its uncond partner's index) and the uncond SHADOW slot
    carries ``shadow_of`` (the cond index) — the shadow holds the same
    handle but is never credited, completed, or evicted on its own; it
    lives and dies with its cond slot.

    ``need`` is the request's total emit budget (text fill + image
    span) when ``image_seq_len_override`` caps the grid, None for a
    full-length request: harvest truncates the final chunk at the
    budget and completes the slot early — the device keeps the full
    sequence shape (one compiled program), the host just stops
    delivering at the override span. ``since_preview`` counts harvested
    chunks since the last progressive-preview request (streaming)."""

    __slots__ = ("handle", "t0", "emitted", "t_admit", "pair",
                 "shadow_of", "need", "since_preview")

    def __init__(self, handle: S.RequestHandle, t0: int, t_admit: float,
                 pair: Optional[int] = None,
                 shadow_of: Optional[int] = None,
                 need: Optional[int] = None):
        self.handle = handle
        self.t0 = t0
        self.emitted: List[int] = []
        self.t_admit = t_admit
        self.pair = pair
        self.shadow_of = shadow_of
        self.need = need
        self.since_preview = 0


class _Chunk:
    """One in-flight fused-decode dispatch: the device-side emit ring and
    post-chunk active mask (still futures until harvested), plus the
    host's snapshot of which request occupied each slot at dispatch time
    — a slot expired and re-admitted while the chunk is in flight must
    not leak the old request's tokens into the new one."""

    __slots__ = ("ring", "active", "owners")

    def __init__(self, ring, active, owners):
        self.ring = ring
        self.active = active
        self.owners = owners


def _p50_ms(samples: List[float]) -> float:
    """Nearest-rank p50 of a list of wall-seconds, in ms (0.0 when
    empty) — the admission-timing surface bench's prefix_compare
    asserts warm-vs-cold prefill cost on."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return round(1e3 * s[min(len(s) // 2, len(s) - 1)], 4)


class _Row:
    """One SLOT's worth of admission plan. A plain request is one row; a
    guided request is two (cond + uncond shadow, ``pair_row`` linking
    them). ``mode`` is the prefix-cache disposition: ``cold`` runs the
    bucket prefill; ``warm`` maps an indexed entry's pages (zero prefill
    FLOPs); ``warm_pending`` is a warm-after — its key is being
    prefilled by an earlier cold row of the SAME admission (the
    N-samples-of-one-prompt fan-out), so it resolves against the index
    after the cold groups land."""

    __slots__ = ("handle", "codes", "uncond", "pair_row", "t0", "bucket",
                 "total_pages", "mode", "shared_n", "key", "entry",
                 "grants", "slot", "group_idx")

    def __init__(self, handle: S.RequestHandle, codes, uncond: bool):
        self.handle = handle
        self.codes = codes
        self.uncond = uncond
        self.pair_row: Optional["_Row"] = None
        self.t0 = len(codes)
        self.bucket = 0
        self.total_pages = 0
        self.mode = "cold"
        self.shared_n = 0
        self.key: Optional[str] = None
        self.entry = None
        self.grants: List[int] = []
        self.slot = -1
        self.group_idx = -1


class Engine:
    """The continuous-batching loop. Pulls from a ``scheduler.RequestQueue``,
    fulfils handles (directly, or through ``complete`` — the postprocess
    hand-off) with ``scheduler.Result``s."""

    def __init__(self, params: dict, cfg, queue: S.RequestQueue, *,
                 num_slots: int = 4,
                 chunk_steps: int = 8,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 complete: Optional[Callable] = None,
                 metrics=None, log_every: int = 0,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 paged_attn: str = "gather",
                 sparse_reads: bool = False,
                 speculative: int = 0,
                 draft_layers: int = 0,
                 prefix_cache: bool = False,
                 prefix_entries: int = 256,
                 preview_every: int = 0,
                 model_version: str = "0",
                 weights_version: str = "0",
                 time_admissions: bool = False,
                 flight_events: int = 256,
                 clock: Callable[[], float] = time.perf_counter,
                 device=None):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.obs import flight as oflight
        from dalle_pytorch_tpu.ops import decode as decode_ops

        # replica placement: committing the params pins every program
        # this engine runs (and, transitively, all its decode state) to
        # ONE device, so a ReplicaSet can put each replica on its own
        # chip and their chunk programs genuinely overlap. device=None
        # (the single-engine default) keeps jax's default placement.
        # Placement flows through the _place_*/_put hooks so a subclass
        # can swap "one device" for "one mesh" (serve/mesh_engine.py's
        # MeshEngine: params/KV pjit-sharded, host-visible state
        # replicated) without touching any of the loop's logic.
        self.device = device
        self.cfg = cfg
        self.params = self._place_params(params)
        params = self.params
        self.queue = queue
        self.num_slots = int(num_slots)
        self.chunk_steps = int(chunk_steps)
        if self.chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        self.complete = complete
        # the flight recorder (docs/OBSERVABILITY.md): the last N
        # structured events + span records, ALWAYS on — no JSONL sink
        # required. Every event this engine emits tees into the ring
        # through the RecordingMetrics wrap (the configured sink, if
        # any, still gets everything it got before), and a fence dumps
        # the ring into the fence event payload so post-mortems don't
        # depend on anyone having configured logging in advance.
        self.flight = oflight.FlightRecorder(capacity=int(flight_events))
        self.metrics = oflight.wrap_metrics(self.flight, metrics)
        self.log_every = int(log_every)
        self.quantize_cache = bool(quantize_cache)
        self.clock = clock
        self.kv = str(kv)
        if self.kv not in ("dense", "paged"):
            raise ValueError(f"kv must be 'dense' or 'paged', got {kv!r}")
        # the paged K/V read implementation: 'gather' materializes the
        # dense view through the block tables (the parity oracle);
        # 'kernel' consumes them in place via the Pallas ragged
        # paged-attention kernel (ops/paged_attention.py) — same fused
        # one-compile emit-ring program, only the per-step read changes
        self.paged_attn = str(paged_attn)
        if self.paged_attn not in ("gather", "kernel"):
            raise ValueError(f"paged_attn must be 'gather' or 'kernel', "
                             f"got {paged_attn!r}")
        if self.paged_attn == "kernel" and self.kv != "paged":
            raise ValueError("paged_attn='kernel' requires kv='paged' "
                             "(the kernel reads the page pool through "
                             "block tables; the dense slot cache has "
                             "neither)")
        # sparsity-aware decode reads: sparse layers read only their
        # statically visible pages (ops.sparse.visible_pages) instead of
        # the whole cached prefix — tokens stay byte-identical (the
        # skipped pages carry exactly-zero attention weight), only the
        # per-token KV read traffic shrinks (docs/SERVING.md "Sparse
        # decode reads"). All three preconditions are typed here, at
        # construction, not as trace-time surprises.
        self.sparse_reads = bool(sparse_reads)
        if self.sparse_reads:
            if self.kv != "paged":
                raise ValueError("sparse_reads requires kv='paged' — "
                                 "page visibility lives in the paged "
                                 "KV layout (block tables)")
            pattern = cfg.transformer.sparse_pattern
            if not any(pattern):
                raise ValueError(
                    "sparse_reads on a config with no sparse layers "
                    "would be a silent no-op (every layer reads the "
                    "full prefix either way) — drop the flag")
            from dalle_pytorch_tpu.ops import transformer as T_ops
            period = T_ops._pattern_period(pattern)
            if period > T_ops._MAX_UNROLL_PERIOD:
                raise ValueError(
                    f"sparse_reads needs a periodic dense/sparse "
                    f"pattern (period <= {T_ops._MAX_UNROLL_PERIOD}) "
                    f"so the per-layer read shapes resolve statically "
                    f"in the fused decode program; pattern {pattern} "
                    f"has period {period}")
        # speculative decode (docs/SERVING.md "Speculative decode"):
        # each fused round drafts k-1 tokens with a shallow early-exit
        # head (the first draft_layers transformer layers + the same
        # logit head — no extra weights) and verifies all k in ONE
        # k-wide full-model pass. Deterministic fold_in(rng, pos)
        # sampling makes acceptance an equality test, so the emitted
        # stream is byte-identical to eager — speculation only changes
        # how many sequential full-depth passes each token costs.
        self.speculative = int(speculative)
        if self.speculative < 0:
            raise ValueError(
                f"speculative must be >= 0, got {speculative}")
        depth = cfg.transformer.depth
        self.draft_layers = int(draft_layers) or max(depth // 2, 1)
        self._draft_cfg = None
        if self.speculative:
            if self.sparse_reads:
                raise ValueError(
                    "speculative does not compose with sparse_reads — "
                    "the k-wide verify reads the full cached prefix "
                    "per query (masked, not trimmed); run one or the "
                    "other")
            if not 1 <= self.draft_layers <= depth:
                raise ValueError(
                    f"draft_layers must be in [1, depth={depth}], "
                    f"got {self.draft_layers}")
            self._draft_cfg = D.draft_transformer_config(
                cfg.transformer, self.draft_layers)
        # the per-dispatch device-pos advance: chunk_steps fused rounds,
        # each emitting up to k tokens (1 when not speculating)
        self._chunk_span = self.chunk_steps * max(self.speculative, 1)

        if prefill_buckets is None:
            buckets = S.prefill_buckets(cfg.text_seq_len)
        else:
            buckets = tuple(sorted(set(int(b) for b in prefill_buckets)))
            if not buckets or buckets[0] < 1 \
                    or buckets[-1] != cfg.text_seq_len:
                raise ValueError(
                    f"prefill_buckets must be >= 1 and end at "
                    f"text_seq_len ({cfg.text_seq_len}), got {buckets}")
        self.buckets = buckets

        S_ = self.num_slots
        self.total_len = cfg.seq_len
        # device state: EVERYTHING the steady-state loop touches stays on
        # device between chunks — the KV cache, per-slot token/position/
        # active mask, RNG keys and sampling knobs. The host writes them
        # only through the admission/kill programs (device-side scatter),
        # and reads only the emit ring, one explicit device_get per
        # chunk. Cache dtype follows the embedding table — the dtype that
        # flows into qkv, so the admission scatter matches what prefill
        # allocates (under bf16 params an f32 default would promote the
        # whole decode carry)
        if self.kv == "paged":
            from dalle_pytorch_tpu.serve import kv_pool as KV
            self.page_size = int(page_size) or min(16, self.total_len)
            if not 1 <= self.page_size <= self.total_len:
                raise ValueError(
                    f"page_size must be in [1, seq_len={self.total_len}], "
                    f"got {self.page_size}")
            if self.paged_attn == "kernel":
                # typed, at pool init, naming the kernel tile constraint
                # — not an opaque Mosaic failure inside pallas_call
                KV.validate_page_size(self.page_size)
            # logical pages one full-length sequence spans = the block
            # table width; also the floor on the pool (ONE request must
            # always be able to run alone, or eviction could livelock)
            self.slot_max_pages = KV.pages_for(self.total_len,
                                               self.page_size)
            full = S_ * self.slot_max_pages + 1   # + trash page
            self.num_pages = int(num_pages) or full
            if self.num_pages - 1 < self.slot_max_pages:
                raise ValueError(
                    f"num_pages={self.num_pages} cannot hold even one "
                    f"full sequence ({self.slot_max_pages} pages of "
                    f"{self.page_size} rows + the reserved trash page)")
            self.cache = self._place_kv(KV.init_page_pool(
                cfg.transformer, self.num_pages, self.page_size,
                dtype=params["text_emb"]["w"].dtype,
                quantized=self.quantize_cache))
            self.alloc = KV.PageAllocator(self.num_pages)
            # the host owns the authoritative block tables (it owns the
            # allocator); the device copy is pushed — one explicit
            # device_put of a few KB — only when the mapping changes
            self._bt_host = np.zeros((S_, self.slot_max_pages), np.int32)
            self.block_tables = self._put(self._bt_host)
            self._bt_dirty = False
            self._slot_pages: List[List[int]] = [[] for _ in range(S_)]
            # safe host-side upper bound of each slot's device pos
            # (t0 + K per dispatched chunk, capped): mapping ahead off
            # this bound can over-allocate by at most one chunk, never
            # lag the device
            self._pos_est = [0] * S_
            self._pages_samples: deque = deque(maxlen=10_000)
            self.evicted = 0
            self.deferred = 0            # DISTINCT page-deferred requests
            self._deferred_ids: set = set()
            # head-of-line page reservation: the oldest page-deferred
            # request's id and need — while set, admission stops popping
            # until that many pages are free, so completions' freed
            # pages accumulate for it instead of being consumed by
            # later, smaller requests (the no-starvation guarantee)
            self._hol_rid: Optional[int] = None
            self._hol_need = 0
            # the smallest prompt span any admission could need: below
            # this many free pages, popping the queue could only churn
            # (pop -> defer -> requeue once per chunk)
            self._min_admit_pages = KV.pages_for(min(self.buckets),
                                                 self.page_size)
        else:
            if prefix_cache:
                raise ValueError(
                    "prefix_cache requires kv='paged' — physical prompt "
                    "sharing lives in the page pool's block-table "
                    "indirection; the dense slot cache has neither "
                    "pages nor refcounts")
            self.cache = self._place_kv(decode_ops.init_cache(
                cfg.transformer, S_, self.total_len,
                dtype=params["text_emb"]["w"].dtype,
                quantized=self.quantize_cache))
        # commit the per-slot state too: nothing this engine carries
        # between chunks may sit on the default device for jit to
        # migrate per call (on a placed replica it lands on its chip;
        # on a mesh engine it is replicated across the slice)
        (self.key_mask, self.cur_tok, self.pos, self.active, self.rng,
         self.temp, self.topk_k, self.top_p) = self._place_state((
            jnp.ones((S_, self.total_len), bool),
            jnp.zeros((S_,), jnp.int32),
            jnp.zeros((S_,), jnp.int32),
            jnp.zeros((S_,), bool),
            jnp.zeros((S_, 2), jnp.uint32),
            jnp.ones((S_,), jnp.float32),
            jnp.ones((S_,), jnp.int32),
            jnp.zeros((S_,), jnp.float32)))
        # classifier-free-guidance pair state: per-slot partner index
        # (self when unpaired), guidance scale (0 = off — the mix and
        # the partner-copy in sample_per_slot are exact identities
        # then), and the uncond-shadow flag. Host-authoritative like the
        # block tables: admission/teardown edit the host arrays and one
        # explicit device_put pushes them before the next chunk.
        self._cfg_partner_host = np.arange(S_, dtype=np.int32)
        self._cfg_scale_host = np.zeros((S_,), np.float32)
        self._cfg_uncond_host = np.zeros((S_,), bool)
        (self.cfg_partner, self.cfg_scale,
         self.cfg_uncond) = self._place_state((
             jnp.arange(S_, dtype=jnp.int32),
             jnp.zeros((S_,), jnp.float32),
             jnp.zeros((S_,), bool)))
        self._cfg_dirty = False
        self.slots: List[Optional[_Slot]] = [None] * S_
        # the weight generation this engine serves: stamped on every
        # Result it fulfils (rolling weight hot-swap makes "which
        # weights produced these tokens" a per-replica fact, and the
        # byte-identity contract holds PER version). Distinct from
        # model_version below, which keys the prefix cache — though the
        # replica set feeds the same string to both, so an upgraded
        # replica can never serve another generation's cached prompt KV.
        self.weights_version = str(weights_version)
        # the prefix cache (kv='paged' only): content-addressed prompt
        # KV sharing over the refcounted allocator
        self.model_version = str(model_version)
        self.prefix = None
        if prefix_cache:
            from dalle_pytorch_tpu.serve import prefix_cache as PC
            self.prefix = PC.PrefixIndex(self.alloc,
                                         max_entries=prefix_entries)
            self._layer_sig = PC.layer_signature(cfg.transformer)
        # admission timing (bench's prefix_compare reads these): wall
        # seconds per cold prefill dispatch / warm admission, measured
        # to completion (block_until_ready) — off by default, because
        # the block is a host sync admission doesn't otherwise need
        self.time_admissions = bool(time_admissions)
        self.prefill_times: List[float] = []
        self.warm_admit_times: List[float] = []
        # progressive image previews (streaming): every preview_every
        # harvested chunks per streaming slot, hand the image-token
        # prefix to on_preview (the postprocess stage pads it to the
        # full grid and decodes it through the ONE batch-1 VAE program
        # — serve/postprocess.py). 0 disables; the hook is set by the
        # server after construction, like ``complete``.
        self.preview_every = int(preview_every)
        if self.preview_every < 0:
            raise ValueError(f"preview_every must be >= 0, got "
                             f"{preview_every}")
        self.on_preview: Optional[Callable] = None
        self.previews_requested = 0
        self._pending: deque = deque()   # dispatched, un-harvested chunks
        # memo for the config-static /stats read-bytes model, keyed by
        # the sparse_reads flag it was asked for
        self._modeled_read_bytes: Dict[bool, int] = {}
        # serve-side profiler capture (POST /admin/profile): armed by
        # request_profile as a REQUEST the engine thread consumes at
        # its next chunk dispatch (so the start index is read on the
        # one thread that advances it — no HTTP-thread race), stopped
        # after a relative countdown of harvests (so the capture covers
        # the device actually executing the chunks, not just the async
        # dispatches). One at a time — jax.profiler's rule.
        self._profile_req: Optional[Tuple[str, int]] = None
        self._profiler = None
        self._profile_left = 0
        self._profile_lock = threading.Lock()
        self.profiles_taken = 0

        # counters (stats()/bench_serve read these)
        self.decode_traces = 0          # bumped only while TRACING: the
        self.prefill_traces = 0         # fixed-shape contract keeps the
        #                                 decode program at 1 and prefill
        #                                 at 1 per bucket
        self.warm_admit_traces = 0      # the warm-admission program: 1,
        #                                 ever (no bucket dependence)
        self._prefill_trace_counts: Dict[int, int] = {}
        self.prefill_runs = 0           # prefill DISPATCHES (a warm hit
        #                                 runs zero of these)
        self.warm_admits = 0            # requests admitted zero-FLOP
        self.prefix_hits = 0            # warm admissions (engine-level:
        #                                 counted when the hit is USED,
        #                                 not merely probed)
        self.cfg_pairs = 0              # guided pairs admitted
        self.reaped = 0                 # externally-cancelled slots
        #                                 reclaimed (stream disconnect,
        #                                 group cancel, hedge loser)
        self.decode_steps = 0           # fused steps dispatched (chunks*K)
        self.harvests = 0               # emit-ring device_gets — the ONLY
        #                                 host syncs in steady state
        self.tokens_decoded = 0
        self.completed = 0
        self.expired = 0
        self.occupancy_sum = 0
        # speculative accounting: DELIVERED tokens over rounds that
        # emitted anything — tokens_decoded/occupancy already count only
        # ring entries >= 0, so rejected drafts never inflate them; these
        # two add the acceptance-rate numerator/denominator
        self.spec_rounds = 0            # verify rounds that delivered
        self.spec_delivered = 0         # tokens those rounds delivered
        self.spec_proposed = 0          # positions those rounds COULD
        #                                 have delivered: k, clamped to
        #                                 the sequence end — so a
        #                                 perfect draft scores exactly
        #                                 1.0, not "1.0 minus the last
        #                                 round's truncation"
        self._t_start = None
        self._last_log = 0

        # replica supervision surface (serve/replica.py): the heartbeat
        # is stamped at every step and every harvest — a wedged device
        # sync stops it advancing, which is how a hang is detected
        # without touching the wedged thread. ``fenced`` is the one-way
        # kill switch the supervisor flips BEFORE reclaiming this
        # engine's in-flight requests: a fenced engine never fulfils a
        # handle, hands a completion downstream, or re-queues anything
        # — its requests belong to whoever fenced it.
        self.fenced = False
        self.last_heartbeat = self.clock()
        # True while a KNOWN first call of a jitted program is tracing/
        # compiling (cold prefill bucket, first decode chunk): compiles
        # take seconds on a cold cache, and the supervisor must not
        # read the stalled heartbeat as a hang and fence a healthy
        # replica mid-compile
        self.compiling = False
        # a fenced engine mid-step may hold handles it just popped that
        # are in neither its queue nor its slots; this hook (set by the
        # replica supervisor) returns them to the shared queue instead
        # of dropping them
        self.on_fenced_orphan: Optional[Callable] = None
        # handles popped from the queue but not yet slotted — published
        # BEFORE admission so a reclaim sweep can see work held by a
        # thread wedged inside the admission prefill (a cold compile
        # blocks for seconds with these in step locals)
        self._admitting: List[S.RequestHandle] = []

        # donating the cache lets XLA update the K/V buffers in place
        # per chunk instead of copying them
        from dalle_pytorch_tpu.parallel._compat import donate_if_accelerator
        donate = donate_if_accelerator(1)
        impl = self._decode_impl_paged if self.kv == "paged" \
            else self._decode_impl
        self._decode_fn = self._jit_decode(impl, donate)
        self._kill_fn = jax.jit(lambda active, keep: active & keep)
        self._prefill_fns: Dict = {}
        self._warm_fn = None            # built lazily (prefix_cache)
        self._install_fn = None         # built lazily (migration import)
        if self.kv == "paged":
            from dalle_pytorch_tpu.serve import kv_pool as KV
            # the page copy pair, shared by the prefix cache's
            # copy-on-write fork AND live migration's export/import:
            # snapshot one physical page, restore one physical page.
            # Pool updates go through the _jit_pool_update hook so a
            # mesh engine can pin the KV shardings — an unpinned
            # restore that drifted the pool's placement would silently
            # retrace the fused decode program (decode_traces catches
            # it, but pin instead of hope).
            self._snap_fn = self._jit_pool_read(
                lambda pool, pid: KV.snapshot_page(pool, pid))
            self._restore_fn = self._jit_pool_update(
                lambda pool, pid, snap: KV.restore_page(pool, pid, snap))
        self._lock = threading.Lock()   # step_once is not reentrant

    # -- placement hooks (the mesh seam: serve/mesh_engine.py) --------------
    #
    # Every host<->device placement the engine performs flows through
    # these five methods, and the two jit hooks own program construction.
    # The base implementations reproduce the single-device behaviour
    # exactly; MeshEngine overrides them to pjit-shard params and the KV
    # store over a device mesh while replicating everything the host
    # protocol touches — which is why the entire serving loop above them
    # (admission, fused chunks, emit-ring harvest, fencing, supervision)
    # runs unmodified on a mesh.

    def _put(self, a):
        """One explicit host->device transfer of a small host array
        (admission tensors, block tables, kill masks)."""
        import jax
        return jax.device_put(a, self.device)

    def _place_params(self, params):
        import jax
        return params if self.device is None \
            else jax.device_put(params, self.device)

    def _place_kv(self, cache: dict) -> dict:
        import jax
        return cache if self.device is None \
            else jax.device_put(cache, self.device)

    def _place_state(self, state: tuple) -> tuple:
        import jax
        return state if self.device is None \
            else jax.device_put(state, self.device)

    def _jit_decode(self, impl, donate):
        import jax
        return jax.jit(impl, donate_argnums=donate)

    def _jit_prefill_program(self, pre):
        import jax
        return jax.jit(pre)

    def _jit_warm_program(self, warm):
        """The warm-admission program (prefix cache): same jit seam as
        prefill — the mesh engine pins replicated output shardings so
        the per-slot state's placement can never drift."""
        import jax
        return jax.jit(warm)

    def _jit_pool_read(self, fn):
        """Page snapshot (prefix insert): pool -> one page's rows."""
        import jax
        return jax.jit(fn)

    def _jit_pool_update(self, fn):
        """Page restore (COW fork): returns the UPDATED pool, so a mesh
        engine must pin the pool's shardings on the output."""
        import jax
        return jax.jit(fn)

    def _logits_sync(self, logits):
        """Traced hook over the per-step logits, identity here. The mesh
        engine re-replicates here: its logits head is vocab-sharded
        (column-parallel, elementwise-exact), and the sampler's softmax/
        cumsum reductions must never run over a sharded axis or the
        byte-identity contract dies to float reassociation."""
        return logits

    def _decode_out_sync(self):
        """The ``ops.decode`` ``out_sync`` seam: None here; the mesh
        engine returns a replicate-constraint applied to the per-head
        attention output before the out projection."""
        return None

    # -- jitted programs ----------------------------------------------------

    def _cfg_closures(self, params, keys, temp, topk_k, top_p, partner,
                      cfgs, uncond):
        """The embed/sample closures BOTH fused decode programs share,
        with per-request classifier-free guidance folded in: a guided
        pair's cond slot samples image positions from the mixed logits
        (its partner's row is the uncond stream — the gather happens on
        the replicated post-``_logits_sync`` logits, so the mesh
        engine's vocab sharding never reorders the mix), the uncond
        shadow copies its partner's drawn token, and the shadow's TEXT
        positions embed PAD — ``generate_images``' guided scan
        verbatim. With every scale at 0 (no guided request in the
        pool) each extra op is an exact identity, so the unguided
        byte-identity contract is untouched."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models import dalle as D

        def embed_fn(tok, p):
            # the null stream's text stays PAD — feeding it the sampled
            # caption would make it conditional (one-shot: cur_tok =
            # where(is_text & uncond_rows, 0, cur_tok))
            tok = jnp.where(uncond & (p < self.cfg.text_seq_len), 0, tok)
            return D.decode_token_embed(params, self.cfg, tok, p)

        def sample_fn(h, pred_pos):
            logits = self._logits_sync(D.to_logits(params, h))
            return D.sample_per_slot(logits, pred_pos, keys, temp,
                                     topk_k, top_p, self.cfg,
                                     partner=partner, cfg_scale=cfgs,
                                     uncond=uncond)

        return embed_fn, sample_fn

    def _decode_impl(self, params, cache, cur_tok, pos, active, keys, temp,
                     topk_k, top_p, partner, cfgs, uncond):
        """The fused steady-state program: ``chunk_steps`` decode steps
        for ALL slots in one ``lax.scan`` (``ops.decode.decode_loop``),
        emitted tokens collected into the device-side (num_slots, K)
        ring. Traced exactly once (fixed shapes) — the side-effecting
        counter below proves it; the guidance-pair state rides as three
        more (num_slots,) arrays, never a new trace."""
        self.decode_traces += 1
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.ops import decode as decode_ops

        embed_fn, sample_fn = self._cfg_closures(
            params, keys, temp, topk_k, top_p, partner, cfgs, uncond)
        if self.speculative:
            # the draft weights are a leading-layers slice of the SAME
            # resident params, taken inside the traced fn so hot-swap,
            # donation and mesh placement all flow through unchanged
            draft_p = D.draft_transformer_params(
                params["transformer"], self.draft_layers)
            return decode_ops.decode_loop_spec(
                params["transformer"], draft_p, cur_tok, pos, active,
                cache, cfg=self.cfg.transformer,
                draft_cfg=self._draft_cfg, key_mask=self.key_mask,
                steps=self.chunk_steps, k=self.speculative,
                embed_fn=embed_fn, sample_fn=sample_fn,
                out_sync=self._decode_out_sync())
        return decode_ops.decode_loop(
            params["transformer"], cur_tok, pos, active, cache,
            cfg=self.cfg.transformer, key_mask=self.key_mask,
            steps=self.chunk_steps, embed_fn=embed_fn, sample_fn=sample_fn,
            out_sync=self._decode_out_sync())

    def _decode_impl_paged(self, params, cache, block_tables, cur_tok, pos,
                           active, keys, temp, topk_k, top_p, partner,
                           cfgs, uncond):
        """The paged twin of ``_decode_impl``: identical fused K-step
        emit-ring program, but K/V reads go through the block tables —
        the dense-view gather, or the in-place Pallas ragged
        paged-attention kernel under ``paged_attn='kernel'`` — and
        writes scatter into the page pool
        (``ops.decode.decode_loop_paged``). The block tables are a
        per-chunk constant — the host maps every page the chunk could
        write before dispatch — so this too traces exactly once."""
        self.decode_traces += 1
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.ops import decode as decode_ops

        embed_fn, sample_fn = self._cfg_closures(
            params, keys, temp, topk_k, top_p, partner, cfgs, uncond)
        if self.speculative:
            draft_p = D.draft_transformer_params(
                params["transformer"], self.draft_layers)
            return decode_ops.decode_loop_spec_paged(
                params["transformer"], draft_p, cur_tok, pos, active,
                cache, block_tables, cfg=self.cfg.transformer,
                draft_cfg=self._draft_cfg, key_mask=self.key_mask,
                total_len=self.total_len, steps=self.chunk_steps,
                k=self.speculative, embed_fn=embed_fn,
                sample_fn=sample_fn, attn_impl=self.paged_attn,
                out_sync=self._decode_out_sync())
        return decode_ops.decode_loop_paged(
            params["transformer"], cur_tok, pos, active, cache,
            block_tables, cfg=self.cfg.transformer,
            key_mask=self.key_mask, total_len=self.total_len,
            steps=self.chunk_steps, embed_fn=embed_fn,
            sample_fn=sample_fn, attn_impl=self.paged_attn,
            sparse_reads=self.sparse_reads,
            out_sync=self._decode_out_sync())

    def _prefill_fn(self, bucket: int):
        """Admission program for one prompt-length BUCKET: batched prefill
        of a full num_slots-row group (prompts padded to ``bucket``,
        unused rows aimed at the dropped out-of-range slot index),
        scatter of the KV rows into the slot pool, each request's FIRST
        sampled token (position t0 = the TRUE prompt length, key
        ``fold_in(rng, t0)`` — ``generate_images``'s first_tok), and the
        device-side merge of the new slots' decode state. Compiled once
        per bucket for the engine's life — group size is pinned at
        num_slots, so no other shape can ever reach it."""
        import jax
        import jax.numpy as jnp
        if bucket in self._prefill_fns:
            return self._prefill_fns[bucket]
        paged = self.kv == "paged"

        def pre(params, cache, cur_tok, pos, active, rng, temp, topk_k,
                top_p, text, lens, slots, n_seed, n_temp,
                n_topk, n_top_p, n_partner, n_cfgs, n_uncond,
                page_rows=None):
            # page_rows rides only the paged trace: dense admission
            # omits it entirely (no dead argument, no wasted transfer)
            self.prefill_traces += 1
            self._prefill_trace_counts[bucket] = \
                self._prefill_trace_counts.get(bucket, 0) + 1
            from dalle_pytorch_tpu.models import dalle as D
            from dalle_pytorch_tpu.ops import decode as decode_ops

            # seed -> key ON DEVICE (identical to the eager
            # PRNGKey(seed) the one-shot path uses): the host ships
            # plain int32 seeds, so admission stays free of implicit
            # transfers under guards.no_transfers
            n_rng = jax.vmap(jax.random.PRNGKey)(n_seed)
            tokens = D.embed_prompt(params, self.cfg, text)
            h, group = decode_ops.prefill(
                params["transformer"], tokens, cfg=self.cfg.transformer,
                total_len=self.total_len, prompt_mask=None,
                quantize_cache=self.quantize_cache,
                out_sync=self._decode_out_sync())
            if paged:
                # scatter the group's [0, bucket) rows into their pages:
                # row j of group-row g lands in physical page
                # page_rows[g, j] (trash 0 for the unused dummy rows) at
                # offset j % page_size. Advanced indices at dims 1 and 3
                # are non-adjacent, so updates are (G, bucket, depth,
                # heads[, dh])
                off = (jnp.arange(bucket) % self.page_size)[None, :]
                rows = {k: group[k][:, :, :, :bucket] for k in group}

                def put(buf, val):
                    if val.ndim == 5:
                        return buf.at[:, page_rows, :, off, :].set(
                            jnp.transpose(val, (1, 3, 0, 2, 4)))
                    return buf.at[:, page_rows, :, off].set(
                        jnp.transpose(val, (1, 3, 0, 2)))

                cache = {k: put(cache[k], rows[k]) for k in cache}
            else:
                cache = {k: cache[k].at[:, slots].set(group[k],
                                                      mode="drop")
                         for k in cache}
            # logits at each row's TRUE last prompt position: rows are
            # padded to the bucket, but causality makes h[:, lens-1]
            # identical to the unpadded prefill's last row
            h_last = jnp.take_along_axis(
                h, (lens - 1)[:, None, None], axis=1)[:, 0]
            logits = self._logits_sync(D.to_logits(params, h_last))
            # n_partner is the GROUP-row index of a guided row's pair
            # (both members admit in the same bucket group: the null
            # caption has the cond prompt's length); the same
            # mix/copy as the fused decode step covers the FIRST
            # sampled token — at position t0 == text_seq_len that
            # token is already an image position and must be guided
            first = D.sample_per_slot(logits, lens, n_rng, n_temp,
                                      n_topk, n_top_p, self.cfg,
                                      partner=n_partner,
                                      cfg_scale=n_cfgs,
                                      uncond=n_uncond)
            cur_tok = cur_tok.at[slots].set(first, mode="drop")
            pos = pos.at[slots].set(lens, mode="drop")
            active = active.at[slots].set(True, mode="drop")
            rng = rng.at[slots].set(n_rng, mode="drop")
            temp = temp.at[slots].set(n_temp, mode="drop")
            topk_k = topk_k.at[slots].set(n_topk, mode="drop")
            top_p = top_p.at[slots].set(n_top_p, mode="drop")
            # h_last rides back out for the prefix cache's insert (the
            # warm path's first token samples from exactly this row)
            return (cache, cur_tok, pos, active, rng, temp, topk_k,
                    top_p, h_last)

        fn = self._jit_prefill_program(pre)
        self._prefill_fns[bucket] = fn
        return fn

    def _warm_admit_fn(self):
        """Admission program for prefix-cache WARM hits: the prompt's KV
        already sits in shared pages and its last hidden row is cached,
        so admission is ONE ``to_logits`` + per-slot first-token sample
        + the device-side state merge — zero transformer FLOPs, and no
        bucket dependence (h_last is (G, dim) whatever the prompt
        length), so it compiles exactly once for the engine's life.
        Byte-identity with the cold path holds because prefill rows are
        batch-row-independent: the cached h_last IS the row the cold
        program would have computed, and the sample math is the same
        ``sample_per_slot`` call."""
        if self._warm_fn is not None:
            return self._warm_fn
        import jax

        def warm(params, cur_tok, pos, active, rng, temp, topk_k, top_p,
                 h_last, lens, slots, n_seed, n_temp, n_topk, n_top_p,
                 n_partner, n_cfgs, n_uncond):
            self.warm_admit_traces += 1
            from dalle_pytorch_tpu.models import dalle as D
            n_rng = jax.vmap(jax.random.PRNGKey)(n_seed)
            logits = self._logits_sync(D.to_logits(params, h_last))
            first = D.sample_per_slot(logits, lens, n_rng, n_temp,
                                      n_topk, n_top_p, self.cfg,
                                      partner=n_partner,
                                      cfg_scale=n_cfgs, uncond=n_uncond)
            cur_tok = cur_tok.at[slots].set(first, mode="drop")
            pos = pos.at[slots].set(lens, mode="drop")
            active = active.at[slots].set(True, mode="drop")
            rng = rng.at[slots].set(n_rng, mode="drop")
            temp = temp.at[slots].set(n_temp, mode="drop")
            topk_k = topk_k.at[slots].set(n_topk, mode="drop")
            top_p = top_p.at[slots].set(n_top_p, mode="drop")
            return cur_tok, pos, active, rng, temp, topk_k, top_p

        self._warm_fn = self._jit_warm_program(warm)
        return self._warm_fn

    # -- request lifecycle --------------------------------------------------

    def fence(self) -> None:
        """One-way kill switch (replica failover / operator drain): after
        this, the engine drops every completion/expiry/error instead of
        fulfilling it, skips every requeue, and ``step_once`` bails on
        entry. Set by the supervisor BEFORE it reclaims this engine's
        in-flight handles, so a wedged thread waking mid-step cannot race
        the replay with a stale result (``RequestHandle.fulfill`` being
        first-write-wins is the belt to this suspender)."""
        self.fenced = True

    def inflight_handles(self) -> List[S.RequestHandle]:
        """Host-side snapshot of every request this engine holds: the
        in-slot handles plus any mid-admission ones (popped, published
        in ``_admitting``, not yet slotted) — the failover reclaim
        surface. Pure bookkeeping (no device sync), so a supervisor can
        read it even while the engine thread is wedged inside a chunk
        or an admission compile."""
        out: List[S.RequestHandle] = []
        seen: set = set()
        for h in [s.handle for s in list(self.slots) if s is not None] \
                + list(self._admitting):
            rid = h.request.request_id
            if rid not in seen:
                seen.add(rid)
                out.append(h)
        return out

    def progress_snapshot(self) -> Dict[int, int]:
        """``{request_id: tokens_emitted_so_far}`` for every in-slot
        request — pure host bookkeeping, no device sync. This is the
        supervision surface that works WITHOUT a shared heap: a child-
        process worker ships it in every heartbeat/harvest frame, and
        the parent's retire math subtracts exactly these prefixes for
        the requests it reclaims (replay re-credits every token, so the
        aggregate keeps counting distinct delivered tokens even though
        parent and child never share memory)."""
        return {s.handle.request.request_id: len(s.emitted)
                for s in list(self.slots)
                if s is not None and s.shadow_of is None}

    def counters(self) -> Dict[str, int]:
        """The ``COUNTERS`` block as a dict (heartbeat/retire surface)."""
        return {k: int(getattr(self, k, 0)) for k in COUNTERS}

    def compile_pending(self) -> bool:
        """True when the NEXT ``step_once`` may block in a trace/compile
        (cold decode program, or a queued prompt whose bucket has no
        compiled prefill yet). A child-process worker cannot stamp a
        heartbeat MID-step the way the in-process loop flips
        ``self.compiling``, so it asks this before stepping and sends a
        compiling=True heartbeat first — otherwise the supervisor would
        read the compile-length silence as a hang and hard-kill a
        healthy child warming up."""
        if self.decode_traces == 0 and (self.active_slots() > 0
                                        or self.queue.depth() > 0):
            return True
        if self.prefix is not None and self.warm_admit_traces == 0 \
                and self.queue.depth() > 0:
            # only when a queued prompt would ACTUALLY admit warm (its
            # key is indexed, or a same-key sibling is queued ahead of
            # it — the warm-after fan-out) does the next step risk the
            # warm program's one-time compile. A blanket True here
            # would stretch a process worker's hang deadline from
            # heartbeat_s to compile_grace_s for the engine's whole
            # life under unique-prompt traffic.
            from dalle_pytorch_tpu.serve import prefix_cache as PC
            seen: set = set()
            for codes, cfg_scale in self.queue.pending_prompt_codes():
                rows = [tuple(int(c) for c in codes)]
                if cfg_scale > 0:
                    rows.append((0,) * len(codes))
                for row in rows:
                    key = PC.prefix_key(
                        row, model_version=self.model_version,
                        layer_sig=self._layer_sig,
                        quantized=self.quantize_cache)
                    if key in self.prefix or key in seen:
                        return True
                    seen.add(key)
        for n in self.queue.pending_prompt_lens():
            try:
                b = S.bucket_for(n, self.buckets)
            except ValueError:
                continue            # admission rejects it, no compile
            if b not in self._prefill_fns:
                return True
        return False

    def _orphan_handles(self, handles) -> None:
        """Hand fenced-mid-step handles back to the supervisor (they
        are in neither this engine's queue nor its slots, so the
        reclaim sweep cannot see them) — the ONE definition of the
        fence-orphan contract, shared by every admission bail-out."""
        for h in handles:
            if not h.done() and self.on_fenced_orphan is not None:
                self.on_fenced_orphan(h)

    def _requeue_or_orphan(self, handle: S.RequestHandle) -> None:
        """Return a handle to the line: via this engine's own queue
        normally, via the supervisor's orphan hook once fenced — the
        fence may land MID-STEP (after the entry checks, while a device
        op blocks), and by then the private queue is drained, so its
        ``requeue`` would fulfil the handle ``cancelled`` and race the
        failover replay with a spurious terminal result."""
        if self.fenced:
            self._orphan_handles([handle])
            return
        self.queue.requeue(handle)

    def _span(self, handle: S.RequestHandle, name: str, now: float,
              **meta) -> None:
        """Stamp one trace span and land the record in the flight ring.
        Pure host bookkeeping (a dict + two list appends) — stamping
        inside the transfer-guarded steady state is free and safe."""
        tr = handle.trace
        if tr is not None:
            self.flight.record(tr.span(name, now, **meta))

    def _finish(self, handle: S.RequestHandle, result: S.Result) -> None:
        if self.fenced:
            return
        result.weights_version = self.weights_version
        if result.status == S.OK and self.complete is not None:
            self.complete(handle, result)
        else:
            handle.fulfill(result)

    def _expire(self, handle: S.RequestHandle, now: float,
                where: str) -> None:
        req = handle.request
        self.expired += 1
        if self.metrics is not None:
            self.metrics.event(**S.structured_event(
                "serve_deadline", request_id=req.request_id, where=where,
                deadline_s=req.deadline_s,
                waited_s=round(now - req.submit_t, 4)))
        self._finish(handle, S.Result(
            status=S.DEADLINE_EXCEEDED, request_id=req.request_id,
            reason=f"deadline_s={req.deadline_s:g} exceeded ({where})",
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _error(self, handle: S.RequestHandle, now: float,
               reason: str) -> None:
        req = handle.request
        if self.metrics is not None:
            self.metrics.event(**S.structured_event(
                "serve_error", request_id=req.request_id, error=reason))
        self._finish(handle, S.Result(
            status=S.ERROR, request_id=req.request_id, reason=reason,
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _cfg_wire(self, i: int, j: int, scale: float) -> None:
        """Host-side pairing of cond slot i with uncond shadow j."""
        self._cfg_partner_host[i] = j
        self._cfg_partner_host[j] = i
        self._cfg_scale_host[i] = np.float32(scale)
        self._cfg_scale_host[j] = np.float32(scale)
        self._cfg_uncond_host[i] = False
        self._cfg_uncond_host[j] = True
        self._cfg_dirty = True

    def _cfg_reset(self, i: int) -> None:
        """Back to unpaired: partner = self, scale 0 (every guidance op
        in the fused program is then an exact identity for slot i)."""
        self._cfg_partner_host[i] = i
        self._cfg_scale_host[i] = 0.0
        self._cfg_uncond_host[i] = False
        self._cfg_dirty = True

    def _sync_cfg(self) -> None:
        """Push the host-authoritative guidance-pair state — same
        explicit-device_put discipline as the block tables."""
        if self._cfg_dirty:
            (self.cfg_partner, self.cfg_scale,
             self.cfg_uncond) = (
                self._put(self._cfg_partner_host),
                self._put(self._cfg_scale_host),
                self._put(self._cfg_uncond_host))
            self._cfg_dirty = False

    def _plan_rows(self, take: List[S.RequestHandle]
                   ) -> Dict[int, List[_Row]]:
        """Expand handles into per-slot admission rows: one for a plain
        request, a cond/uncond pair for a guided one (the uncond row
        runs the all-PAD null caption of the SAME length, so the pair
        always lands in one prefill bucket)."""
        per_handle: Dict[int, List[_Row]] = {}
        for h in take:
            r = h.request
            rc = _Row(h, tuple(int(c) for c in r.codes), uncond=False)
            hrows = [rc]
            if r.cfg_scale > 0:
                ru = _Row(h, (0,) * len(r.codes), uncond=True)
                rc.pair_row = ru
                ru.pair_row = rc
                hrows.append(ru)
            for p in hrows:
                p.bucket = S.bucket_for(p.t0, self.buckets)
            per_handle[r.request_id] = hrows
        return per_handle

    def _classify_row(self, p: _Row, pending: set) -> None:
        """Prefix-cache disposition of one row (paged mode). The lookup
        verifies the stored token tuple, so a hash collision reads as a
        miss, never as another prompt's KV."""
        from dalle_pytorch_tpu.serve import kv_pool as KV
        from dalle_pytorch_tpu.serve import prefix_cache as PC
        p.total_pages = KV.pages_for(p.bucket, self.page_size)
        if self.prefix is None:
            return
        p.key = PC.prefix_key(p.codes, model_version=self.model_version,
                              layer_sig=self._layer_sig,
                              quantized=self.quantize_cache)
        p.entry = self.prefix.lookup(p.key, p.codes)
        if p.entry is not None:
            p.mode = "warm"
            p.shared_n = len(p.entry.full_pages)
        elif p.key in pending:
            # an earlier cold row of THIS admission is prefilling the
            # same prompt (the N-samples fan-out): admit warm after
            # its insert lands — the shared span is allocated once
            p.mode = "warm_pending"
            p.shared_n = p.t0 // self.page_size

    def _admit(self, handles: List[S.RequestHandle], now: float) -> None:
        if self.fenced:
            # fenced mid-step after the pop: these handles are in
            # neither the queue nor a slot, so the reclaim sweep cannot
            # see them — hand them back to the shared queue (replay)
            # rather than dropping them on the floor
            self._orphan_handles(handles)
            return
        free = [i for i, s in enumerate(self.slots) if s is None]
        valid = []
        for h in handles:
            if h.done():
                # cancelled while queued (stream disconnect, group
                # cancel, hedge loser): its terminal result already
                # stuck — slotting it would decode tokens nobody reads
                continue
            # the server's queue validates at submit; a raw queue may
            # not — a prompt the pool can't hold must become a typed
            # error result, never a crash of the serving loop
            n = len(h.request.codes)
            if not 1 <= n <= self.cfg.text_seq_len:
                self._error(h, now, f"invalid prompt length {n} "
                            f"(need 1..{self.cfg.text_seq_len})")
                continue
            if h.request.cfg_scale > 0 and self.num_slots < 2:
                self._error(h, now, "cfg_scale needs a cond/uncond "
                            "slot pair: num_slots must be >= 2")
                continue
            L = int(h.request.image_seq_len_override)
            if L and not 1 <= L <= self.cfg.image_seq_len:
                self._error(h, now, f"image_seq_len_override {L} out "
                            f"of range (need 1.."
                            f"{self.cfg.image_seq_len})")
                continue
            valid.append(h)
        # slot budget in arrival order: a guided request takes TWO
        # slots, so the pop (one handle per free slot) can overshoot —
        # the overflow re-enters at its original position, never drops
        budget = len(free)
        take: List[S.RequestHandle] = []
        for k, h in enumerate(valid):
            width = 2 if h.request.cfg_scale > 0 else 1
            if width > budget:
                for hh in valid[k:]:
                    self._requeue_or_orphan(hh)
                break
            budget -= width
            take.append(h)
        per_handle = self._plan_rows(take)

        rows: List[_Row] = []
        if self.kv == "paged" and take:
            # admission is gated on FREE PAGES, not just free slots: the
            # prompt span (rows [0, bucket), which prefill writes) must
            # be mapped up front. The fit check runs in ARRIVAL order
            # (pop_ready's priority+seq order, BEFORE bucket grouping)
            # and stops at the first request that doesn't fit: it and
            # everything behind it are re-queued — typed backpressure,
            # not a drop. The blocked head's need is remembered
            # (``_hol_need``) and step_once stops popping until that
            # many pages are free; with requeue preserving arrival
            # order, later/smaller requests can never consume the pages
            # freed for it. A full sequence always fits the pool alone
            # (constructor invariant), so the head always eventually
            # fits and no request starves. Need is PREFIX-AWARE: a warm
            # row pays only its private span, and the LRU end of the
            # prefix index is dropped before a request is deferred.
            fits: List[S.RequestHandle] = []
            pending: set = set()
            for k, h in enumerate(take):
                rid = h.request.request_id
                hrows = per_handle[rid]
                for p in hrows:
                    self._classify_row(p, pending)
                if len(hrows) == 2:
                    # a MIXED pair (one side warm, one cold) admits
                    # whole-cold: the pair's first token mixes both
                    # streams' logits inside ONE program, and that
                    # program is the bucket prefill
                    modes = {p.mode for p in hrows}
                    if "cold" in modes and modes != {"cold"}:
                        for p in hrows:
                            p.mode, p.shared_n, p.entry = "cold", 0, None
                for p in hrows:
                    if p.mode == "cold" and p.key is not None:
                        pending.add(p.key)
                need = sum(p.total_pages - p.shared_n for p in hrows)
                if self.alloc.free < need and self.prefix is not None:
                    # cached prefixes are a perf lever, live requests
                    # are work: drop LRU entries before deferring
                    self.prefix.shrink(need)
                if self.alloc.free < need:
                    # head-of-line block: requeue this and every later
                    # pop (arrival order preserved by queue_seq)
                    for hh in take[k:]:
                        self._requeue_or_orphan(hh)
                    self._hol_rid = rid
                    self._hol_need = need
                    # a waiting request is re-popped once it could fit;
                    # count it (and log it) only on the transition INTO
                    # the deferred state, so stats()["deferred"] means
                    # distinct requests, not churn
                    if rid not in self._deferred_ids:
                        self._deferred_ids.add(rid)
                        self.deferred += 1
                        if self.metrics is not None:
                            self.metrics.event(**S.structured_event(
                                "serve_page_defer",
                                request_id=rid,
                                pages_needed=need,
                                pages_free=self.alloc.free))
                    break
                for p in hrows:
                    p.grants = self.alloc.alloc(
                        p.total_pages - p.shared_n)
                fits.append(h)
                rows.extend(hrows)
                self._deferred_ids.discard(rid)
                if rid == self._hol_rid:
                    self._hol_rid = None
                    self._hol_need = 0
            take = fits
        else:
            for h in take:
                rows.extend(per_handle[h.request.request_id])

        free = self._admit_cold(rows, free, now)
        self._admit_warm(rows, free, now)

    def _admit_cold(self, rows: List[_Row], free: List[int],
                    now: float) -> List[int]:
        """Bucket-grouped prefill admission of the plan's cold rows.
        Returns the free-slot indices left for the warm phase."""
        groups: Dict[int, List[_Row]] = {}
        for p in rows:
            if p.mode == "cold":
                groups.setdefault(p.bucket, []).append(p)
        for bucket, group in groups.items():
            if self.fenced:
                # fenced between groups: the rest of the admission is
                # step locals the reclaim sweep cannot see — orphan
                # them back to the shared queue
                self._orphan_handles(self._unique_handles(group))
                continue
            idx = free[:len(group)]
            free = free[len(group):]
            G = self.num_slots
            # fixed-shape group: prompts padded to the bucket, unused
            # rows parked on slot index num_slots — out of range, so
            # every scatter drops them (mode='drop' in the program)
            text = np.zeros((G, bucket), np.int32)
            lens = np.ones((G,), np.int32)
            slots = np.full((G,), self.num_slots, np.int32)
            # paged only — unused rows' prompt rows scatter into the
            # trash page 0; dense prefill takes no page_rows at all
            page_rows = np.zeros((G, bucket), np.int32) \
                if self.kv == "paged" else None
            n_seed = np.zeros((G,), np.int32)
            n_temp = np.ones((G,), np.float32)
            n_topk = np.ones((G,), np.int32)
            n_top_p = np.zeros((G,), np.float32)
            n_partner = np.arange(G, dtype=np.int32)
            n_cfgs = np.zeros((G,), np.float32)
            n_uncond = np.zeros((G,), bool)
            for j, p in enumerate(group):
                p.slot, p.group_idx = idx[j], j
                self._fill_admit_row(p, j, lens, n_seed, n_temp, n_topk,
                                     n_top_p, n_cfgs, n_uncond)
                text[j, :p.t0] = p.codes
                slots[j] = idx[j]
                if self.kv == "paged":
                    self._bt_host[idx[j], :] = 0
                    self._bt_host[idx[j], :len(p.grants)] = p.grants
                    page_rows[j] = self._bt_host[
                        idx[j], np.arange(bucket) // self.page_size]
            for j, p in enumerate(group):
                # a pair's rows always share the bucket, hence the group
                if p.pair_row is not None and p.pair_row in group:
                    n_partner[j] = p.pair_row.group_idx
            try:
                # explicit-transfer discipline: the admission path's
                # host->device traffic is device_put at the site, never
                # implicit conversion (guards.no_transfers-clean).
                # device=None is jax's default placement; a placed
                # replica ships straight to its own chip, a mesh engine
                # replicates across its slice
                put = self._put
                cold = bucket not in self._prefill_fns
                if cold:
                    self.compiling = True
                try:
                    t_pre = self.clock()
                    outs = self._prefill_fn(bucket)(
                        self.params, self.cache, self.cur_tok, self.pos,
                        self.active, self.rng, self.temp, self.topk_k,
                        self.top_p, put(text), put(lens), put(slots),
                        put(n_seed), put(n_temp), put(n_topk),
                        put(n_top_p), put(n_partner), put(n_cfgs),
                        put(n_uncond),
                        **({"page_rows": put(page_rows)}
                           if self.kv == "paged" else {}))
                    self.prefill_runs += 1
                    if self.time_admissions and not cold:
                        import jax
                        jax.block_until_ready(outs[1])
                        self.prefill_times.append(self.clock() - t_pre)
                finally:
                    if cold:
                        self.compiling = False
                        self.last_heartbeat = self.clock()
            except Exception as e:  # noqa: BLE001 — no-hangs contract
                # the group's slots were never assigned (still None) and
                # the device state is rebound only on success below, so
                # the pool stays consistent; the group's callers get a
                # typed error instead of hanging on a dead loop
                if self.kv == "paged":
                    for j, p in enumerate(group):
                        self.alloc.release(p.grants)
                        p.grants = []
                        self._bt_host[idx[j], :] = 0
                    self._bt_dirty = True
                for h in self._unique_handles(group):
                    self._error(h, now, f"prefill failed: {e!r}")
                continue
            if self.fenced:
                # fence landed DURING the prefill call (a cold compile
                # is seconds long — exactly where a supervisor's hang
                # deadline can fire): the reclaim sweep could not see
                # this group (neither queued nor slotted, just step
                # locals), so hand it back to the shared queue instead
                # of slotting it into a dead engine
                self._orphan_handles(self._unique_handles(group))
                continue
            (self.cache, self.cur_tok, self.pos, self.active, self.rng,
             self.temp, self.topk_k, self.top_p, h_last) = outs
            t_slotted = self.clock()
            for p in group:
                i = p.slot
                self.slots[i] = _Slot(p.handle, p.t0, now,
                                      need=self._slot_need(
                                          p.handle.request, p.t0))
                if self.kv == "paged":
                    self._slot_pages[i] = list(p.grants)
                    self._pos_est[i] = p.t0
                    self._bt_dirty = True
                if not p.uncond:    # one admit span per request, not
                    #                 per slot of a guided pair
                    self._span(p.handle, "prefill_admit", t_slotted,
                               bucket=bucket, mode="cold", slot=i)
            self._wire_pairs(group)
            if self.prefix is not None:
                for p in group:
                    self._prefix_insert(p, h_last)
        return free

    def _fill_admit_row(self, p: _Row, j: int, lens, n_seed, n_temp,
                        n_topk, n_top_p, n_cfgs, n_uncond) -> None:
        """One admission row's sampling knobs (shared by the cold and
        warm programs; an uncond shadow carries its cond request's
        knobs — its own draw is overwritten by the partner copy)."""
        req = p.handle.request
        lens[j] = p.t0
        # two's-complement truncation to int32: PRNGKey keeps
        # only the low 32 bits under the default x64-off mode,
        # so this is value-identical to PRNGKey(seed) eager
        s = int(req.seed) & 0xFFFFFFFF
        n_seed[j] = s - (1 << 32) if s >= (1 << 31) else s
        n_temp[j] = np.float32(req.sampling.temperature)
        n_topk[j] = max(
            int((1 - req.sampling.filter_thres) * self.cfg.total_tokens),
            1)
        n_top_p[j] = np.float32(req.sampling.top_p)
        n_cfgs[j] = np.float32(req.cfg_scale)
        n_uncond[j] = p.uncond

    def _slot_need(self, req: S.Request, t0: int) -> Optional[int]:
        """The slot's total emit budget under ``image_seq_len_override``
        (text fill + capped image span), None for a full-length request.
        Decode stops at the budget on the HOST — harvest truncates the
        final chunk and completes the slot early — so the one compiled
        full-length program serves every override; the cost ceiling is
        at most one chunk of wasted device steps past the cap."""
        L = int(req.image_seq_len_override)
        if not L:
            return None
        return (self.cfg.text_seq_len - t0) + L

    def _unique_handles(self, group: List[_Row]) -> List[S.RequestHandle]:
        out, seen = [], set()
        for p in group:
            rid = p.handle.request.request_id
            if rid not in seen:
                seen.add(rid)
                out.append(p.handle)
        return out

    def _wire_pairs(self, group: List[_Row]) -> None:
        """Link freshly slotted cond/uncond pairs (host bookkeeping +
        the device-side partner/scale/uncond state)."""
        for p in group:
            if p.pair_row is None or p.uncond:
                continue
            i, j = p.slot, p.pair_row.slot
            self.slots[i].pair = j
            self.slots[j].shadow_of = i
            self._cfg_wire(i, j, p.handle.request.cfg_scale)
            self.cfg_pairs += 1

    def _prefix_insert(self, p: _Row, h_last) -> None:
        """Index a cold row's freshly prefilled prompt span: the full
        prompt pages (retained by the index), a COW snapshot of the
        partial boundary page, and the last hidden row. Taken NOW —
        before any decode chunk can write rows >= t0 into the boundary
        page — so the cached copy is immutable from here on."""
        if p.key is None or p.key in self.prefix:
            return
        from dalle_pytorch_tpu.serve import prefix_cache as PC
        i = p.slot
        s_full = p.t0 // self.page_size
        snap = None
        if p.t0 % self.page_size:
            pid = self._slot_pages[i][s_full]
            snap = self._snap_fn(self.cache, self._put(np.int32(pid)))
        self.prefix.insert(PC.PrefixEntry(
            p.key, p.codes, p.t0, self._slot_pages[i][:s_full], snap,
            h_last[p.group_idx]))

    def _admit_warm(self, rows: List[_Row], free: List[int],
                    now: float) -> None:
        """Zero-prefill admission of the plan's warm rows: map shared
        pages (refcount++), fork boundary pages copy-on-write, and run
        the ONE warm-admission program for first tokens + state merge."""
        warm: List[_Row] = []
        for p in rows:
            if p.mode not in ("warm", "warm_pending"):
                continue
            if p.pair_row is not None and p.uncond:
                continue            # handled with its cond row below
            hrows = [p] + ([p.pair_row] if p.pair_row is not None else [])
            resolved = True
            for q in hrows:
                if q.entry is None:
                    q.entry = self.prefix.lookup(q.key, q.codes)
                if q.entry is None \
                        or len(q.entry.full_pages) != q.shared_n:
                    # the cold sibling whose insert this warm-after
                    # rode never landed (its prefill failed): give the
                    # pages back and retry cold next pop
                    resolved = False
            if not resolved or self.fenced:
                for q in hrows:
                    if q.grants:
                        self.alloc.release(q.grants)
                        q.grants = []
                self._requeue_or_orphan(p.handle)
                continue
            warm.extend(hrows)
        if not warm:
            return
        import jax
        import jax.numpy as jnp
        G = self.num_slots
        lens = np.ones((G,), np.int32)
        slots = np.full((G,), self.num_slots, np.int32)
        n_seed = np.zeros((G,), np.int32)
        n_temp = np.ones((G,), np.float32)
        n_topk = np.ones((G,), np.int32)
        n_top_p = np.zeros((G,), np.float32)
        n_partner = np.arange(G, dtype=np.int32)
        n_cfgs = np.zeros((G,), np.float32)
        n_uncond = np.zeros((G,), bool)
        h_rows = []
        mapped: List[_Row] = []
        coldw = self.warm_admit_traces == 0
        if coldw:
            self.compiling = True
        try:
            try:
                for j, p in enumerate(warm):
                    i = free[j]
                    p.slot, p.group_idx = i, j
                    entry = p.entry
                    # the tentpole's read-only-sharing proof, asserted
                    # at every warm mapping: shared pages all lie wholly
                    # below t0, and decode only ever appends at
                    # positions >= t0 — so _store_rows_paged can never
                    # scatter into a shared page
                    assert p.t0 >= p.shared_n * self.page_size, \
                        "shared prefix pages must end at/below the " \
                        "prompt length"
                    self.alloc.retain(entry.full_pages)
                    mapped.append(p)
                    pages = list(entry.full_pages) + list(p.grants)
                    self._bt_host[i, :] = 0
                    self._bt_host[i, :len(pages)] = pages
                    if entry.boundary_snap is not None:
                        # COW fork: the consumer's private boundary page
                        # starts as a byte copy of the cached one, then
                        # diverges under its own decode writes
                        self.cache = self._restore_fn(
                            self.cache, self._put(np.int32(p.grants[0])),
                            entry.boundary_snap)
                    self._fill_admit_row(p, j, lens, n_seed, n_temp,
                                         n_topk, n_top_p, n_cfgs,
                                         n_uncond)
                    slots[j] = i
                    h_rows.append(entry.h_last)
                for j, p in enumerate(warm):
                    if p.pair_row is not None and p.pair_row in warm:
                        n_partner[j] = p.pair_row.group_idx
                if len(h_rows) < G:
                    # pad with a live row, not zeros_like (whose fill
                    # scalar would be an implicit host->device
                    # transfer): pad rows scatter to the dropped
                    # out-of-range slot index, so their values never
                    # land anywhere
                    h_rows = h_rows + [h_rows[0]] * (G - len(h_rows))
                h_stack = jnp.stack(h_rows)
                put = self._put
                t_warm = self.clock()
                outs = self._warm_admit_fn()(
                    self.params, self.cur_tok, self.pos, self.active,
                    self.rng, self.temp, self.topk_k, self.top_p,
                    h_stack, put(lens), put(slots), put(n_seed),
                    put(n_temp), put(n_topk), put(n_top_p),
                    put(n_partner), put(n_cfgs), put(n_uncond))
                if self.time_admissions and not coldw:
                    jax.block_until_ready(outs[0])
                    self.warm_admit_times.append(self.clock() - t_warm)
            finally:
                if coldw:
                    self.compiling = False
                    self.last_heartbeat = self.clock()
        except Exception as e:  # noqa: BLE001 — no-hangs contract
            # nothing was slotted: give back every reference the
            # mapping loop took (shared retains + private grants) and
            # the un-mapped rows' grants, then fail the callers typed
            for p in warm:
                if p in mapped:
                    self.alloc.release(list(p.entry.full_pages)
                                       + list(p.grants))
                    self._bt_host[p.slot, :] = 0
                elif p.grants:
                    self.alloc.release(p.grants)
                p.grants = []
            self._bt_dirty = True
            for h in self._unique_handles(warm):
                self._error(h, now, f"warm admission failed: {e!r}")
            return
        if self.fenced:
            # same contract as the prefill-call fence: not slotted, so
            # the reclaim sweep cannot see these — orphan them
            self._orphan_handles(self._unique_handles(warm))
            return
        (self.cur_tok, self.pos, self.active, self.rng, self.temp,
         self.topk_k, self.top_p) = outs
        t_slotted = self.clock()
        for p in warm:
            i = p.slot
            self.slots[i] = _Slot(p.handle, p.t0, now,
                                  need=self._slot_need(
                                      p.handle.request, p.t0))
            self._slot_pages[i] = list(p.entry.full_pages) + \
                list(p.grants)
            self._pos_est[i] = p.t0
            self._bt_dirty = True
            self.prefix_hits += 1
            self.warm_admits += 1
            if not p.uncond:
                self._span(p.handle, "prefill_admit", t_slotted,
                           mode="warm", slot=i,
                           pages_shared=p.shared_n)
            if self.metrics is not None:
                self.metrics.event(**S.structured_event(
                    "serve_prefix_hit",
                    request_id=p.handle.request.request_id,
                    uncond=p.uncond, pages_shared=p.shared_n,
                    pages_private=len(p.grants)))
        self._wire_pairs(warm)

    # -- page-pool lifecycle (kv='paged') -----------------------------------

    def _release_slot_pages(self, i: int) -> None:
        """Drop slot i's page REFERENCES back to the pool and zero its
        block-table row (completion/expiry/eviction/terminate). Under
        prefix sharing a reference drop is not necessarily a free: a
        shared prompt page stays resident while the index (or a sibling
        slot) still maps it — the refcounted allocator frees only at
        zero. The stale device-side row needs no urgent push: the dead
        slot's writes are redirected to the trash page inside the
        program (active=False), and reads of re-assigned pages are
        causally masked."""
        if self._slot_pages[i]:
            self.alloc.release(self._slot_pages[i])
            self._slot_pages[i] = []
        self._bt_host[i, :] = 0
        self._pos_est[i] = 0
        self._bt_dirty = True

    def _free_slot(self, i: int) -> List[int]:
        """The one slot-teardown path (completion/expiry/eviction/
        terminate): vacate the slot and, in paged mode, return its page
        references to the pool — forgetting the paged branch would leak
        pages until the pool wedged, so no call site spells it out by
        hand. A guided pair tears down ATOMICALLY: freeing the cond
        slot frees its uncond shadow too (the shadow is never freed on
        its own — it has no life of its own to end). Returns the freed
        slot indices, so callers that must clear device active bits
        (expiry, eviction) kill every member."""
        slot = self.slots[i]
        freed = [i]
        self.slots[i] = None
        if self.kv == "paged":
            self._release_slot_pages(i)
        self._cfg_reset(i)
        j = slot.pair if slot is not None else None
        if j is not None and self.slots[j] is not None \
                and self.slots[j].shadow_of == i:
            self.slots[j] = None
            if self.kv == "paged":
                self._release_slot_pages(j)
            self._cfg_reset(j)
            freed.append(j)
        return freed

    def _evict_lowest_priority(self, now: float) -> bool:
        """The PagePoolExhausted backpressure path: evict the LOWEST-
        priority active request (highest priority value; ties broken by
        latest admission) back to the queue. Its page references are
        dropped — under sharing, a page a sibling (or the prefix index)
        still maps stays OUT of the free list: the refcounted release
        is what makes eviction safe next to copy-on-write sharing — its
        device slot(s) killed, and its handle re-queued intact; on
        re-admission, deterministic sampling (same seed, same fold_in
        positions) replays its exact token stream, so eviction costs
        latency, never correctness. A guided pair evicts whole. Returns
        False when no slot is active."""
        if self.fenced:
            return False    # the reclaim sweep owns every in-slot handle
        cand = [(s.handle.request.priority, s.t_admit, i)
                for i, s in enumerate(self.slots)
                if s is not None and s.shadow_of is None]
        if not cand:
            return False
        _, _, i = max(cand)
        slot = self.slots[i]
        free_before = self.alloc.free
        killed = self._free_slot(i)
        freed = self.alloc.free - free_before
        keep = np.ones((self.num_slots,), bool)
        keep[killed] = False
        self.active = self._kill_fn(self.active, self._put(keep))
        self.evicted += 1
        # un-credit the victim's harvested tokens: re-admission replays
        # them all, so leaving the prefix counted would inflate
        # tokens_decoded/mean_occupancy by one prefix per eviction (the
        # same double-count _harvest_chunk avoids by dropping the
        # orphaned mid-flight ring row)
        self.tokens_decoded -= len(slot.emitted)
        self.occupancy_sum -= len(slot.emitted)
        # the eviction is a visible timeline marker: the victim's next
        # spans (re-queue wait, re-admission, full replay) follow it
        self._span(slot.handle, "evict", now, pages_freed=freed)
        self._requeue_or_orphan(slot.handle)
        if self.metrics is not None:
            self.metrics.event(**S.structured_event(
                "serve_evict", request_id=slot.handle.request.request_id,
                priority=slot.handle.request.priority, pages_freed=freed,
                pages_free=self.alloc.free,
                waited_s=round(now - slot.handle.request.submit_t, 4)))
        return True

    def _map_ahead(self, now: float) -> None:
        """Grow-by-one-page, BEFORE every chunk dispatch: each active
        slot's block table must map every row the K fused steps could
        write ([pos, pos+K)), so a page-boundary crossing inside the
        chunk never needs a host sync. Growth works off the host's safe
        pos upper bound (``_pos_est``); when the free list runs dry the
        typed ``PagePoolExhausted`` is converted into evictions of the
        lowest-priority active request until the remainder fits (a full
        sequence always fits the pool alone, so this terminates — in the
        limit the growing slot evicts itself and re-queues)."""
        from dalle_pytorch_tpu.serve import kv_pool as KV
        for i in range(self.num_slots):
            while self.slots[i] is not None:
                # _chunk_span covers the SPECULATIVE horizon: a chunk
                # can write up to chunk_steps*k rows, and every one must
                # find its page mapped (rejected offsets write too —
                # their rows are stale-by-invariant, not unmapped)
                target = min(self._pos_est[i] + self._chunk_span,
                             self.total_len)
                short = KV.pages_for(target, self.page_size) \
                    - len(self._slot_pages[i])
                if short <= 0:
                    break
                if self.alloc.free < short and self.prefix is not None:
                    # drop cached prefixes (LRU first) before evicting
                    # live work — an index-held page a live slot no
                    # longer shares frees immediately at release
                    self.prefix.shrink(short)
                if self.alloc.free >= short:
                    for p in self.alloc.alloc(short):
                        self._bt_host[i, len(self._slot_pages[i])] = p
                        self._slot_pages[i].append(p)
                    self._bt_dirty = True
                    break
                # pool exhausted mid-decode: typed backpressure — the
                # victim may be slot i itself, which ends its while loop
                if not self._evict_lowest_priority(now):
                    # unreachable while slot i is active (it is its own
                    # candidate); defensive: never spin on a dry pool
                    break

    def _sync_block_tables(self) -> None:
        """Push the host's authoritative block tables to the device when
        the mapping changed — ONE explicit device_put of a few KB, the
        only paged-specific host->device traffic in steady state."""
        if self._bt_dirty:
            self.block_tables = self._put(self._bt_host)
            self._bt_dirty = False

    # -- the fused-chunk pipeline -------------------------------------------

    def _dispatch_chunk(self, now: float) -> None:
        """Launch one K-step fused program from the current device state
        and queue its emit ring for a later harvest. No host sync here:
        the outputs are futures, and the device starts computing while
        the host goes on to admit/harvest."""
        cold = self.decode_traces == 0      # first call traces+compiles
        if self._profile_req is not None and self._profiler is None \
                and self.decode_traces > 0:
            # consume the armed request HERE, on the engine thread that
            # advances the dispatch counter — "profile the next K
            # chunks" starts at exactly this dispatch, whatever index
            # it happens to be (an HTTP-thread-precomputed index could
            # be skipped forever if a dispatch raced the arm). Never on
            # the COLD dispatch: a trace wrapping the one-time decode
            # compile swamps the capture AND its stop-time xplane
            # serialization can stall this thread past the replica
            # hang deadline — a capture armed before warm-up simply
            # begins at the first steady-state chunk
            with self._profile_lock:
                req, self._profile_req = self._profile_req, None
            if req is not None:
                from dalle_pytorch_tpu.utils.profiling import StepProfiler
                log_dir, chunks = req
                start = self.decode_steps // self.chunk_steps
                prof = StepProfiler(log_dir, start=start, steps=chunks)
                # stop after the chunks already in flight (they harvest
                # first, FIFO) plus ours have ALL harvested — a relative
                # countdown, immune to any dispatch/harvest skew a past
                # fail_active left behind
                self._profile_left = len(self._pending) + chunks
                # publish BEFORE start_trace: the call can block for
                # seconds syncing behind another replica's in-flight
                # compile, and profile_active() is the supervisor's
                # hang-deadline exemption for exactly that stall
                self._profiler = prof
                try:
                    prof.maybe_start(start)
                except BaseException:
                    self._profiler = None
                    raise
        if cold:
            self.compiling = True
        try:
            self._sync_cfg()
            if self.kv == "paged":
                self._map_ahead(now)
                self._sync_block_tables()
                self._pages_samples.append(self.alloc.in_use)
                outs = self._decode_fn(self.params, self.cache,
                                       self.block_tables, self.cur_tok,
                                       self.pos, self.active, self.rng,
                                       self.temp, self.topk_k, self.top_p,
                                       self.cfg_partner, self.cfg_scale,
                                       self.cfg_uncond)
            else:
                outs = self._decode_fn(self.params, self.cache,
                                       self.cur_tok, self.pos,
                                       self.active, self.rng, self.temp,
                                       self.topk_k, self.top_p,
                                       self.cfg_partner, self.cfg_scale,
                                       self.cfg_uncond)
        finally:
            if cold:
                self.compiling = False
                self.last_heartbeat = self.clock()
        self.cur_tok, self.pos, self.active, self.cache, ring = outs
        owners = [(i, s) for i, s in enumerate(self.slots)
                  if s is not None]
        if self.kv == "paged":
            for i, _ in owners:
                self._pos_est[i] = min(self._pos_est[i] + self._chunk_span,
                                       self.total_len)
        self._pending.append(_Chunk(ring, self.active, owners))
        self.decode_steps += self.chunk_steps

    def _harvest_chunk(self) -> None:
        """Fetch the OLDEST in-flight chunk's emit ring — the single
        explicit host sync per K steps. Distributes each slot's tokens
        to its owner at dispatch time and completes slots whose request
        finished inside the chunk. Completion is timestamped HERE: a
        request that emitted its last token mid-chunk becomes observable
        to its caller only when the ring lands on the host, so harvest
        time is the honest fulfillment time (docs/SERVING.md)."""
        import jax
        rec = self._pending.popleft()
        ring, active_after = jax.device_get([rec.ring, rec.active])
        self.harvests += 1
        if self._profiler is not None:
            # chunks harvest FIFO, so the countdown set at capture
            # start reaches zero exactly when the LAST captured chunk
            # has finished EXECUTING (the device_get above synced it),
            # not merely been dispatched
            self._profile_left -= 1
            if self._profile_left <= 0:
                self._finish_profile()
        now = self.clock()
        # the harvest's device_get is the one blocking sync in steady
        # state — exactly where a wedged device stalls the thread, so
        # stamping the heartbeat here makes the supervisor's missed-
        # heartbeat deadline measure real progress, not loop liveness
        self.last_heartbeat = now
        emitted = 0
        kill: List[int] = []
        for i, slot in rec.owners:
            if slot.shadow_of is not None:
                # uncond shadow of a guided pair: its ring row mirrors
                # the cond stream (partner copy) — crediting it would
                # double-count delivered tokens, and it completes with
                # its cond slot, never on its own
                continue
            if slot.handle.done() or self.slots[i] is not slot:
                # expired/killed/errored/EVICTED since dispatch — its
                # ring row is dead (an evicted request replays every
                # token on re-admission, so crediting these to the
                # orphaned slot would double-count them in
                # tokens_decoded/occupancy), and slot i may already
                # belong to a newer request whose tokens start in a
                # later chunk
                continue
            row = ring[i]
            toks = row[row >= 0]
            capped = False
            if slot.need is not None:
                # image_seq_len_override: the device decodes the full
                # sequence shape, the host stops delivering at the
                # budget — truncate the final chunk and complete early
                left = slot.need - len(slot.emitted)
                if len(toks) >= left:
                    toks = toks[:left]
                    capped = True
            slot.emitted.extend(int(t) for t in toks)
            emitted += len(toks)
            sink = slot.handle.sink
            if sink is not None and len(toks):
                # live token stream: positions are absolute sequence
                # offsets (>= text_seq_len means image tokens), which
                # is what lets the sink dedupe an eviction/failover
                # REPLAY — re-delivered positions below its high-water
                # mark are dropped, so the consumer sees each position
                # exactly once. Never blocks: overflow is the sink's
                # typed drop policy, not engine backpressure.
                sink.push_tokens(
                    slot.t0 + len(slot.emitted) - len(toks),
                    [int(t) for t in toks])
                if (self.on_preview is not None and self.preview_every
                        and not capped):
                    slot.since_preview += 1
                    img_done = len(slot.emitted) \
                        - (self.cfg.text_seq_len - slot.t0)
                    if slot.since_preview >= self.preview_every \
                            and img_done > 0:
                        slot.since_preview = 0
                        self.previews_requested += 1
                        prefix = np.asarray(
                            slot.emitted[self.cfg.text_seq_len
                                         - slot.t0:], np.int32)
                        self.on_preview(slot.handle, prefix)
            if self.speculative:
                # acceptance accounting over DELIVERED tokens only: a
                # round's k-wide ring window holds its accepted prefix,
                # -1 past it — rejected drafts never reach the host, so
                # tokens_decoded/occupancy stay exact for free. The
                # denominator is each round's true potential (k,
                # clamped to the sequence end — pos before the round is
                # recoverable by walking the windows cumulatively), so
                # a full-depth draft scores exactly 1.0
                kk = self.speculative
                pos_cursor = slot.t0 + len(slot.emitted) - len(toks)
                for w in ring[i].reshape(-1, kk):
                    n = int((w >= 0).sum())
                    if n == 0:
                        continue
                    self.spec_rounds += 1
                    self.spec_proposed += min(
                        kk, self.total_len - pos_cursor)
                    pos_cursor += n
                self.spec_delivered += len(toks)
            if self.kv == "paged" and self.speculative:
                # tighten the host's pos bound with the truth the ring
                # just delivered: the dispatch-time advance assumed full
                # acceptance (k per round), so under low acceptance the
                # estimate (and page map-ahead) would creep ahead of the
                # device; pos == t0 + len(emitted) is exact, plus one
                # full span per chunk still in flight
                later = sum(1 for c in self._pending
                            if any(j == i for j, _ in c.owners))
                exact = slot.t0 + len(slot.emitted)
                bound = min(exact + self._chunk_span * later,
                            self.total_len)
                self._pos_est[i] = min(self._pos_est[i], bound)
                if slot.pair is not None:
                    # the uncond shadow's stream is the partner copy —
                    # identical accepted lengths, identical pos
                    self._pos_est[slot.pair] = \
                        min(self._pos_est[slot.pair], bound)
            if len(toks):
                # per-chunk decode attribution: one span per harvested
                # chunk per request, tiling from the previous harvest
                # (or the admit) to THIS harvest — where the request's
                # decode milliseconds actually went
                self._span(slot.handle, "decode_chunk", now,
                           tokens=int(len(toks)))
            if capped:
                # the budget is met mid-sequence: the device bit is
                # still up, so completion must also kill the slot's
                # mask entry (and its shadow's) or the freed slot
                # would keep decoding a ghost
                pair = slot.pair
                self._complete(i, slot, now)
                kill.append(i)
                if pair is not None:
                    kill.append(pair)
            elif not bool(active_after[i]):
                self._complete(i, slot, now)
        if kill:
            keep = np.ones((self.num_slots,), bool)
            keep[kill] = False
            self.active = self._kill_fn(self.active, self._put(keep))
        self.tokens_decoded += emitted
        self.occupancy_sum += emitted

    def _complete(self, i: int, slot: _Slot, now: float) -> None:
        """Fulfil a finished slot's request and free the slot (its device
        state already parked itself inside the fused program)."""
        req = slot.handle.request
        full = list(req.codes) + slot.emitted
        # override requests deliver their capped span (full holds
        # text_seq_len + L tokens then — the host stopped at the budget)
        L = int(req.image_seq_len_override) or self.cfg.image_seq_len
        img_seq = np.asarray(full[-L:], np.int32)
        # the completed text span (prompt + sampled text tokens) —
        # generate_images' full[:, :text_seq_len], what CLIP rerank
        # scores (postprocess.py)
        text_seq = np.asarray(full[:self.cfg.text_seq_len], np.int32)
        self.completed += 1
        self._finish(slot.handle, S.Result(
            status=S.OK, request_id=req.request_id, tokens=img_seq,
            text_tokens=text_seq,
            queued_s=round(slot.t_admit - req.submit_t, 6),
            decode_s=round(now - slot.t_admit, 6),
            total_s=round(now - req.submit_t, 6)))
        self._free_slot(i)

    # -- live migration (kv='paged') ----------------------------------------
    #
    # A slot's entire decode state is movable: KV pages (fp32 or
    # int8+scales), block-table order, device sampling state (pos,
    # cur_tok, the base RNG key, temp/topk/top_p), the CFG shadow, and
    # the host's emitted-token prefix. export_slot snapshots all of it
    # into one JSON-safe payload (MIGRATE frames CRC+seq-check it like
    # every other frame) and vacates the slot WITHOUT fulfilling or
    # requeueing the handle — the request now lives in the payload, and
    # import_slot installs it on a target engine with freshly allocated
    # pages. Byte-identity holds because sampling is deterministic in
    # (rng row, position) — fold_in(key, pos) — and every input to the
    # fused program's next step ships: the continuation is the exact
    # token stream the undisturbed run would have produced. Any failure
    # is the typed MigrationError; the caller falls back to replay.

    def _migrate_install_fn(self):
        """The import-side state merge: same ``.at[slots].set`` scatter
        as warm admission (unused rows aimed at the dropped out-of-range
        index), but with pos/cur_tok/rng taken verbatim from the
        exported device rows instead of re-derived. Compiled once."""
        if self._install_fn is not None:
            return self._install_fn

        def install(cur_tok, pos, active, rng, temp, topk_k, top_p,
                    slots, n_tok, n_pos, n_rng, n_temp, n_topk, n_top_p):
            cur_tok = cur_tok.at[slots].set(n_tok, mode="drop")
            pos = pos.at[slots].set(n_pos, mode="drop")
            active = active.at[slots].set(True, mode="drop")
            rng = rng.at[slots].set(n_rng, mode="drop")
            temp = temp.at[slots].set(n_temp, mode="drop")
            topk_k = topk_k.at[slots].set(n_topk, mode="drop")
            top_p = top_p.at[slots].set(n_top_p, mode="drop")
            return cur_tok, pos, active, rng, temp, topk_k, top_p

        self._install_fn = self._jit_warm_program(install)
        return self._install_fn

    def find_slot(self, request_id: int) -> Optional[int]:
        """The cond slot index holding ``request_id`` (None when not
        in-slot — queued, mid-admission, or already gone)."""
        for i, s in enumerate(self.slots):
            if s is not None and s.shadow_of is None \
                    and s.handle.request.request_id == int(request_id):
                return i
        return None

    def _export_pages(self, pages: List[int]) -> List[dict]:
        import jax
        out = []
        for pid in pages:
            snap = self._snap_fn(self.cache, self._put(np.int32(pid)))
            host = jax.device_get(snap)
            out.append({k: _pack_array(v) for k, v in host.items()})
        return out

    def export_slot(self, i: int):
        """Snapshot slot ``i``'s full decode state into a JSON-safe
        migration payload and VACATE the slot (pages released, device
        active bit cleared, handle neither fulfilled nor requeued — the
        caller owns it now). A guided pair exports atomically: the
        uncond shadow's pages and device rows ride in the same payload.
        Returns ``(payload, handle)``; raises the typed
        ``MigrationError`` on any precondition failure, leaving the
        slot untouched."""
        import jax
        with self._lock:
            if self.fenced:
                raise MigrationError("fenced")
            if self.kv != "paged":
                raise MigrationError(
                    "kv_dense", "migration moves KV pages; the dense "
                    "slot cache has none")
            # flush the in-flight pipeline first: the device pos and the
            # host's emitted list must describe the SAME point in the
            # stream, and no orphaned ring row may outlive the export
            while self._pending:
                # racelint: disable=RL003 — deliberate: _lock IS the
                # step serializer; an export must flush (and sync) under
                # it or the snapshot tears against a concurrent step
                self._harvest_chunk()
            slot = self.slots[i] if 0 <= i < self.num_slots else None
            if slot is None or slot.shadow_of is not None:
                raise MigrationError("not_found", f"slot {i}")
            if slot.handle.done():
                # completed inside the flushed chunks — nothing to move
                raise MigrationError("not_found",
                                     "request completed during export")
            now = self.clock()
            # racelint: disable=RL003 — deliberate: the exported decode
            # state must be fetched under the step serializer, or the
            # snapshot tears against a concurrent step
            snap = jax.device_get((self.pos, self.cur_tok, self.rng,
                                   self.temp, self.topk_k, self.top_p))
            (pos_h, tok_h, rng_h, temp_h, topk_h, topp_h) = snap

            def rows(j):
                return {"pos": int(pos_h[j]), "cur_tok": int(tok_h[j]),
                        "rng": [int(x) for x in rng_h[j]],
                        "temp": float(temp_h[j]),
                        "topk_k": int(topk_h[j]),
                        "top_p": float(topp_h[j]),
                        "pages": self._export_pages(self._slot_pages[j])}

            payload = {
                "format": 1,
                "request_id": int(slot.handle.request.request_id),
                "handle": slot.handle.to_wire(now),
                "emitted": [int(t) for t in slot.emitted],
                "t0": int(slot.t0),
                "weights_version": self.weights_version,
                "page_size": int(self.page_size),
                "quantized": bool(self.quantize_cache),
                "cond": rows(i),
                "uncond": None,
            }
            j = slot.pair
            if j is not None and self.slots[j] is not None \
                    and self.slots[j].shadow_of == i:
                payload["uncond"] = rows(j)
                payload["uncond"]["cfg_scale"] = float(
                    slot.handle.request.cfg_scale)
            handle = slot.handle
            self._span(handle, "migrate_out", now,
                       slot=i, pos=int(pos_h[i]),
                       tokens_done=len(slot.emitted))
            killed = self._free_slot(i)
            keep = np.ones((self.num_slots,), bool)
            keep[killed] = False
            self.active = self._kill_fn(self.active, self._put(keep))
            return payload, handle

    def export_request(self, request_id: int):
        """``export_slot`` addressed by request id (the MIGRATE_OUT
        frame's form — a parent names requests, not slot indices)."""
        i = self.find_slot(request_id)
        if i is None:
            raise MigrationError("not_found", f"request {request_id} "
                                 "is not in a slot on this engine")
        return self.export_slot(i)

    def import_slot(self, payload: dict,
                    handle: Optional[S.RequestHandle] = None) -> int:
        """Install an exported slot on THIS engine: allocate fresh
        pages, restore the snapshot into them, scatter the exported
        device rows into free slot(s), and resume harvesting where the
        source left off. ``handle`` is the live handle in-process
        (thread replicas); None reconstructs a stand-in from the
        payload's wire form (a child worker). Returns the cond slot
        index; raises the typed ``MigrationError`` (target unchanged)
        when the request cannot land here."""
        with self._lock:
            if self.fenced:
                raise MigrationError("fenced")
            if self.kv != "paged":
                raise MigrationError("kv_dense")
            if str(payload.get("weights_version")) != self.weights_version:
                raise MigrationError(
                    "weights_version",
                    f"snapshot from {payload.get('weights_version')!r}, "
                    f"target serves {self.weights_version!r} — tokens "
                    "are byte-identical PER weight generation only")
            if int(payload.get("page_size", 0)) != self.page_size:
                raise MigrationError(
                    "page_size", f"snapshot pages hold "
                    f"{payload.get('page_size')} rows, target pool "
                    f"holds {self.page_size}")
            if bool(payload.get("quantized")) != self.quantize_cache:
                raise MigrationError(
                    "layout", "int8-KV snapshot into an fp32 pool (or "
                    "vice versa)")
            now = self.clock()
            if handle is None:
                handle = S.RequestHandle.from_wire(payload["handle"], now)
            parts = [payload["cond"]]
            if payload.get("uncond") is not None:
                parts.append(payload["uncond"])
            free = [k for k, s in enumerate(self.slots) if s is None]
            if len(free) < len(parts):
                raise MigrationError(
                    "target_slots", f"need {len(parts)} free slots, "
                    f"have {len(free)}")
            need = sum(len(p["pages"]) for p in parts)
            if self.alloc.free < need and self.prefix is not None:
                self.prefix.shrink(need)
            try:
                grants = self.alloc.alloc(need)
            except Exception as e:
                raise MigrationError(
                    "target_pages", f"need {need} pages: {e}") from e
            idx = free[:len(parts)]
            G = self.num_slots
            slots_arr = np.full((G,), G, np.int32)
            n_tok = np.zeros((G,), np.int32)
            n_pos = np.zeros((G,), np.int32)
            n_rng = np.zeros((G, 2), np.uint32)
            n_temp = np.ones((G,), np.float32)
            n_topk = np.ones((G,), np.int32)
            n_top_p = np.zeros((G,), np.float32)
            try:
                taken = 0
                for j, part in enumerate(parts):
                    k = idx[j]
                    pages = grants[taken:taken + len(part["pages"])]
                    taken += len(part["pages"])
                    for pid, packed in zip(pages, part["pages"]):
                        snap = {key: self._put(_unpack_array(packed[key]))
                                for key in packed}
                        self.cache = self._restore_fn(
                            self.cache, self._put(np.int32(pid)), snap)
                    self._bt_host[k, :] = 0
                    self._bt_host[k, :len(pages)] = pages
                    self._slot_pages[k] = list(pages)
                    self._pos_est[k] = int(part["pos"])
                    slots_arr[j] = k
                    n_tok[j] = np.int32(part["cur_tok"])
                    n_pos[j] = np.int32(part["pos"])
                    n_rng[j] = np.asarray(part["rng"], np.uint32)
                    n_temp[j] = np.float32(part["temp"])
                    n_topk[j] = np.int32(part["topk_k"])
                    n_top_p[j] = np.float32(part["top_p"])
                self._bt_dirty = True
                put = self._put
                (self.cur_tok, self.pos, self.active, self.rng,
                 self.temp, self.topk_k, self.top_p) = \
                    self._migrate_install_fn()(
                        self.cur_tok, self.pos, self.active, self.rng,
                        self.temp, self.topk_k, self.top_p,
                        put(slots_arr), put(n_tok), put(n_pos),
                        put(n_rng), put(n_temp), put(n_topk),
                        put(n_top_p))
            except Exception as e:  # noqa: BLE001 — discard, never wedge
                # a torn/corrupt snapshot mid-install: discard the
                # partial import whole (no slot was assigned, the
                # device active bits were never raised) so the source's
                # replay fallback owns the request — page contents
                # written before the failure are unreachable garbage
                # behind the zeroed block-table rows
                self.alloc.release(grants)
                for k in idx:
                    self._bt_host[k, :] = 0
                    self._slot_pages[k] = []
                    self._pos_est[k] = 0
                self._bt_dirty = True
                raise MigrationError("transfer", repr(e)) from e
            i = idx[0]
            t0 = int(payload["t0"])
            # the emit budget is re-derived from the request riding the
            # payload's wire form (legacy frames decode override=0 →
            # full length), so a capped request completes at the same
            # token on the target as it would have at the source
            self.slots[i] = _Slot(handle, t0, now,
                                  need=self._slot_need(handle.request,
                                                       t0))
            self.slots[i].emitted = [int(t) for t in payload["emitted"]]
            if len(parts) == 2:
                j = idx[1]
                self.slots[j] = _Slot(handle, t0, now, shadow_of=i)
                self.slots[i].pair = j
                self._cfg_wire(i, j, payload["uncond"]["cfg_scale"])
            self._span(handle, "migrate_in", now, slot=i,
                       pos=int(payload["cond"]["pos"]),
                       tokens_done=len(payload["emitted"]))
            return i

    # -- the loop -----------------------------------------------------------

    def active_slots(self) -> int:
        return sum(s is not None for s in self.slots)

    def step_once(self) -> bool:
        """One engine iteration: expire, admit, dispatch ONE fused
        K-step chunk, harvest the previous one. Returns True when any
        work happened.

        Transfer discipline: the steady-state loop performs NO implicit
        host<->device traffic at all — per-slot decode state never
        leaves the device, admission writes it through device_put +
        jitted scatter, and the one host read is ``_harvest_chunk``'s
        explicit ``jax.device_get`` of the emit ring, once per K steps
        and overlapped with the next chunk's compute. Tests pin the
        whole iteration (including a mid-stream join) under
        ``analysis.guards.no_transfers()``."""
        with self._lock:
            if self.fenced:
                if self._profiler is not None:
                    # a capture orphaned by the fence: close it on THIS
                    # thread (jax.profiler is process-global — left
                    # open it would poison every future capture and
                    # crash the next start_trace anywhere in-process)
                    self._profiler.close()
                    self._profiler = None
                return False        # reclaimed: this pool is dead weight
            now = self.clock()
            self.last_heartbeat = now
            if self._t_start is None:
                self._t_start = now

            did = False
            # mid-decode deadlines: chunk-boundary granularity — a slot
            # past its deadline is cancelled before the next chunk is
            # dispatched (its bit in the device mask is cleared, so the
            # in-flight chunk's leftover tokens die with the owner check)
            kill = []
            for i, slot in enumerate(self.slots):
                if slot is None or slot.shadow_of is not None:
                    continue        # a shadow expires with its cond slot
                if slot.handle.done():
                    # cancelled externally mid-decode (stream client
                    # disconnected, group cancelled, hedge lost): the
                    # terminal result already stuck via first-write-
                    # wins — reclaim the slot and its pages NOW instead
                    # of decoding to the end for nobody
                    self.reaped += 1
                    if self.metrics is not None:
                        self.metrics.event(**S.structured_event(
                            "serve_slot_reaped",
                            request_id=slot.handle.request.request_id,
                            tokens_done=len(slot.emitted)))
                    kill.extend(self._free_slot(i))
                    continue
                dt = slot.handle.request.deadline_t
                if dt is not None and now > dt:
                    self._expire(slot.handle, now, where="decoding")
                    kill.extend(self._free_slot(i))
            if kill:
                keep = np.ones((self.num_slots,), bool)
                keep[kill] = False
                self.active = self._kill_fn(self.active, self._put(keep))
                did = True

            free = self.num_slots - self.active_slots()
            if self.kv == "paged":
                # don't pop just to defer/requeue every chunk (n=0 still
                # reaps queued deadline expiries): with a head-of-line
                # request waiting, hold admission until ITS need is
                # free — freed pages accumulate for it; otherwise the
                # floor is the smallest bucket's prompt span
                floor = self._hol_need if self._hol_rid is not None \
                    else self._min_admit_pages
                if self.alloc.free < floor and self.prefix is not None \
                        and self.queue.depth() > 0:
                    # an idle pool held hostage by cached prefixes
                    # would gate admission forever: shrink the LRU end
                    # until the floor could pop
                    self.prefix.shrink(floor)
                if self.alloc.free < floor:
                    free = 0
            ready, expired = self.queue.pop_ready(free, now)
            for h in expired:
                self._expire(h, now, where="queued")
                if self.kv == "paged":
                    self._deferred_ids.discard(h.request.request_id)
                    if h.request.request_id == self._hol_rid:
                        self._hol_rid = None
                        self._hol_need = 0
            for h in ready:
                # queue_wait closes HERE for a single-engine pop; a
                # replica-set router already stamped it at routing
                # (has_in_attempt keeps the two shapes from double-
                # counting), and a page-deferred re-pop folds its extra
                # wait into the next prefill_admit span
                if h.trace is not None \
                        and not h.trace.has_in_attempt("queue_wait"):
                    self._span(h, "queue_wait", now)
            if ready:
                # published for the reclaim sweep BEFORE admission can
                # block on a compile (see _admitting)
                self._admitting = list(ready)
                try:
                    # racelint: disable=RL003 — deliberate: admission
                    # compiles/donates into live slot buffers; it MUST
                    # run under the step serializer (_lock), and the
                    # reclaim sweep uses a timed acquire + _admitting
                    # precisely so a slow compile cannot wedge it
                    self._admit(ready, now)
                finally:
                    self._admitting = []
            did = did or bool(ready or expired)

            dispatched = False
            if self.active_slots() > 0:
                self._dispatch_chunk(now)
                dispatched = did = True

            # double buffer: while dispatching, keep exactly one chunk
            # in flight un-harvested — the device_get below blocks on
            # chunk N while the device computes chunk N+1. Once nothing
            # new is dispatched (pool drained), flush the pipeline.
            target = 1 if dispatched else 0
            while len(self._pending) > target:
                # racelint: disable=RL003 — deliberate: the harvest
                # device_get is THE step; _lock is the step serializer,
                # and the double-buffer above already bounds the stall
                # to one chunk
                self._harvest_chunk()
                did = True

            if self._profiler is not None and not dispatched \
                    and not self._pending:
                # the engine drained before the capture's K chunks ran:
                # close it NOW with what it got (partial but valid) —
                # an open process-global trace slows every replica in
                # this process until the next traffic arrives, and "the
                # next K chunks" cannot honestly outlive the work
                self._finish_profile(partial=True)

            if (self.metrics is not None and self.log_every
                    and self.decode_steps - self._last_log
                    >= self.log_every):
                self._last_log = self.decode_steps
                self.metrics.event(event="serve", **self.stats())
            return did

    def idle(self) -> bool:
        """True when there is nothing left to do: queue empty, every slot
        free, every in-flight chunk harvested. The termination predicate
        for any caller driving ``step_once`` by hand (``run_until_idle``,
        bench_serve's budget-compare loop)."""
        return self.queue.depth() == 0 and self.active_slots() == 0 \
            and not self._pending

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Drive until the queue is empty, every slot is free, and every
        in-flight chunk is harvested (tests, bench). ``max_steps`` is a
        runaway guard, not a budget."""
        for _ in range(max_steps):
            busy = self.step_once()
            if not busy and self.idle():
                return
        raise RuntimeError(f"engine did not go idle in {max_steps} steps")

    def run(self, stop: threading.Event, idle_sleep_s: float = 0.002):
        """Serving loop for a dedicated thread (serve.server): spin while
        there is work, nap briefly when idle. An exception out of
        ``step_once`` must NOT kill the loop — one bad step would leave
        every queued and future request hanging forever while /healthz
        still answers. Instead the implicated in-slot requests are
        fulfilled with typed ``error`` results, the pool is reset to a
        consistent idle state, and serving continues."""
        while not stop.is_set():
            try:
                busy = self.step_once()
            except Exception as e:  # noqa: BLE001 — no-hangs contract
                # recovery FIRST, observability second: a raising
                # metrics sink must not kill the thread before the
                # in-slot handles are fulfilled
                n = self.fail_active(f"engine step failed: {e!r}")
                if self.metrics is not None:
                    try:
                        self.metrics.event(**S.structured_event(
                            "serve_engine_error", error=repr(e),
                            failed=n))
                    except Exception:   # noqa: BLE001
                        pass
                stop.wait(idle_sleep_s)     # never hot-spin on a
                continue                    # persistent fault
            if not busy and self.idle():
                stop.wait(idle_sleep_s)
        if self._profiler is not None:
            # clean shutdown with a capture in flight: stop the
            # process-global trace (partial but valid) on the way out
            self._profiler.close()
            # racelint: disable=RL001 — _profiler is run-loop-thread-
            # private (armed via the _profile_req handoff); this is the
            # loop's own epilogue, no other thread ever writes it
            self._profiler = None

    def _terminate_active(self, status: str, reason: str) -> int:
        """Fulfil every in-slot request with a typed terminal result and
        reset the pool to idle (slot state may be mid-update on the error
        path, and in-flight chunks may hold poisoned futures, so the only
        consistent continuation is an empty pool and an empty pipeline).
        Returns the number terminated."""
        import jax.numpy as jnp
        if self.fenced:
            return 0        # the reclaim sweep owns the in-slot handles
        n = 0
        with self._lock:
            now = self.clock()
            for i, slot in enumerate(self.slots):
                if slot is None or slot.shadow_of is not None:
                    continue        # a shadow dies with its cond slot
                req = slot.handle.request
                slot.handle.fulfill(S.Result(
                    status=status, request_id=req.request_id,
                    reason=reason,
                    weights_version=self.weights_version,
                    queued_s=round(slot.t_admit - req.submit_t, 6),
                    total_s=round(now - req.submit_t, 6)))
                self._free_slot(i)
                n += 1
            self._pending.clear()
            if self._profiler is not None:
                # the chunks a capture was waiting on died with the
                # pipeline; close the trace (partial but valid) rather
                # than leaving jax.profiler wedged open forever
                self._profiler.close()
                self._profiler = None
            self.cur_tok = jnp.zeros((self.num_slots,), jnp.int32)
            self.pos = jnp.zeros((self.num_slots,), jnp.int32)
            self.active = jnp.zeros((self.num_slots,), bool)
            self._sync_cfg()
            if self.kv == "paged":
                self._sync_block_tables()
        return n

    def fail_active(self, reason: str) -> int:
        """Typed ``error`` results for every in-slot request — the
        run-loop's recovery path after an unexpected step failure."""
        return self._terminate_active(S.ERROR, reason)

    def cancel_active(self, reason: str = "server shutdown") -> int:
        """Typed ``cancelled`` results for every in-slot request — the
        shutdown path (the no-hangs contract must cover requests already
        admitted, not just queued ones)."""
        return self._terminate_active(S.CANCELLED, reason)

    # -- observability ------------------------------------------------------

    def _finish_profile(self, partial: bool = False) -> None:
        """Stop the in-flight capture and emit ``serve_profile_done``
        (``partial`` when the engine drained before the requested K
        chunks ran). Engine-thread only."""
        prof = self._profiler
        if prof is None:
            return
        # close BEFORE clearing: stop_trace serializes the xplane for
        # seconds, and profile_active() must stay true the whole time —
        # it is the supervisor's hang-deadline exemption (clearing
        # first opens a window where a sweep sees a stale heartbeat,
        # no capture, and fences a healthy replica mid-serialization)
        prof.close()
        self._profiler = None
        self.profiles_taken += 1
        rec = S.structured_event(
            "serve_profile_done", dir=prof.log_dir,
            chunks=prof.stop_at - prof.start)
        if partial:
            rec["partial"] = True
        self.metrics.event(**rec)

    def request_profile(self, log_dir: str, chunks: int = 8) -> dict:
        """Arm a ``jax.profiler`` capture over the NEXT ``chunks`` fused
        decode chunks (``POST /admin/profile``; reuses
        ``utils.profiling.StepProfiler``). The capture starts at the
        next STEADY-STATE chunk dispatch (the one-time cold compile is
        never captured — it would swamp the trace and stall the serving
        thread past supervision deadlines) and stops once that many
        chunks have been harvested — kernel tuning on a real chip
        without stopping the server. Typed ``ProfileError`` (reason
        ``capture_active``, HTTP 409) while a capture is in flight:
        jax.profiler allows exactly one trace at a time."""
        chunks = int(chunks)
        if chunks < 1:
            raise ValueError(f"chunks must be >= 1, got {chunks}")
        if not log_dir:
            raise ValueError("request_profile needs a log_dir "
                             "(serve_dalle --profile_dir sets the "
                             "default sink)")
        with self._profile_lock:
            prof = self._profiler
            if prof is not None:
                raise ProfileError(S.structured_event(
                    "serve_profile_reject", reason="capture_active",
                    dir=prof.log_dir, start_chunk=prof.start,
                    chunks=prof.stop_at - prof.start))
            if self._profile_req is not None:
                raise ProfileError(S.structured_event(
                    "serve_profile_reject", reason="capture_active",
                    dir=self._profile_req[0],
                    chunks=self._profile_req[1]))
            self._profile_req = (str(log_dir), chunks)
        rec = S.structured_event(
            "serve_profile_armed", dir=str(log_dir), chunks=chunks,
            # advisory: the engine thread picks the REAL start index at
            # its next dispatch (_dispatch_chunk consumes the request)
            start_chunk=self.decode_steps // self.chunk_steps)
        self.metrics.event(**rec)
        return rec

    def profile_active(self) -> bool:
        """A capture is pending or running — the arm-time 409 surface
        (a second arm must be refused in either state)."""
        return self._profiler is not None or self._profile_req is not None

    def capturing(self) -> bool:
        """A jax.profiler trace is actually RUNNING (start_trace called
        or in progress, not yet closed) — the supervision-exemption
        surface. An armed-but-not-yet-started request slows nothing,
        and exempting it would let a wedged replica that never reaches
        its next dispatch evade the hang deadline forever."""
        return self._profiler is not None

    def prefill_trace_count(self, bucket: int) -> int:
        """Traces of one bucket's prefill program (contract: <= 1 for the
        engine's life; the guards.compile_count counter in tests)."""
        return self._prefill_trace_counts.get(bucket, 0)

    def kv_hbm_bytes(self) -> int:
        """Resident HBM bytes of the KV store — the page pool under
        ``kv='paged'``, the full slot cache under ``kv='dense'`` (what
        bench_serve's budget comparison reads)."""
        from dalle_pytorch_tpu.serve import kv_pool as KV
        return KV.pool_bytes(self.cache)

    def modeled_kv_read_bytes_per_token(self, sparse_reads=None) -> int:
        """Analytic per-token KV READ bytes for this engine's decode
        configuration (paged mode only; 0 otherwise) — HBM counters are
        not host-observable, so /stats carries the model
        (``ops.paged_attention.modeled_kv_read_bytes_per_token``,
        averaged over a decode span starting at the smallest prefill
        bucket). ``sparse_reads=False`` asks for the dense-reads
        baseline of the same config, which is how /stats can show the
        dense-vs-sparse read ratio this engine is getting. Config-
        static, so the value is computed once per mode and memoized —
        /stats, /healthz, and worker STATS frames poll this."""
        if self.kv != "paged":
            return 0
        sr = self.sparse_reads if sparse_reads is None else bool(sparse_reads)
        if sr in self._modeled_read_bytes:
            return self._modeled_read_bytes[sr]
        from dalle_pytorch_tpu.ops import paged_attention as PA
        tcfg = self.cfg.transformer
        out = int(PA.modeled_kv_read_bytes_per_token(
            depth=tcfg.depth, heads=tcfg.heads, dim_head=tcfg.dim_head,
            total_len=self.total_len, page_size=self.page_size,
            prompt_len=min(self.buckets),
            itemsize=self.cache["k"].dtype.itemsize,
            impl=self.paged_attn, quantized=self.quantize_cache,
            sparse_reads=sr,
            sparse_pattern=tcfg.sparse_pattern if sr else None,
            sparse_block=tcfg.sparse_block, causal=tcfg.causal))
        self._modeled_read_bytes[sr] = out
        return out

    def pages_in_use_p95(self) -> int:
        """Nearest-rank p95 of pages in use, sampled at every chunk
        dispatch (paged mode only; 0 before any dispatch)."""
        if self.kv != "paged" or not self._pages_samples:
            return 0
        s = sorted(self._pages_samples)
        return s[min(int(0.95 * len(s)), len(s) - 1)]

    def _mesh_stats(self) -> dict:
        """The mesh-observability block /stats carries (mesh satellite):
        a plain engine is one chip, and its whole KV store lives there.
        ``MeshEngine`` overrides with its mesh shape and the per-SHARD
        residency — where the pool actually lives."""
        return {"devices_per_replica": 1,
                "mesh_shape": None,
                "kv_hbm_bytes_per_shard": self.kv_hbm_bytes()}

    def stats(self) -> dict:
        elapsed = None if self._t_start is None \
            else max(self.clock() - self._t_start, 1e-9)
        paged = {}
        if self.kv == "paged":
            paged = {
                "paged_attn": self.paged_attn,
                "sparse_reads": self.sparse_reads,
                # modeled per-token KV read traffic, current mode vs the
                # dense-reads baseline — the pair whose ratio is the
                # sparse-reads win (equal when sparse_reads is off)
                "kv_read_bytes_per_token":
                    self.modeled_kv_read_bytes_per_token(),
                "kv_read_bytes_per_token_dense_reads":
                    self.modeled_kv_read_bytes_per_token(
                        sparse_reads=False),
                "page_size": self.page_size,
                "num_pages": self.num_pages,
                # PHYSICAL pages: the refcounted allocator counts a
                # page shared by N block tables (or held by the prefix
                # index) exactly once, which is what keeps this gauge —
                # and kv_hbm_bytes, the pool's resident bytes — exact
                # under sharing
                "pages_in_use": self.alloc.in_use,
                "pages_free": self.alloc.free,
                "pages_peak": self.alloc.peak_in_use,
                "pages_in_use_p95": self.pages_in_use_p95(),
                "pages_shared": self.alloc.pages_shared,
                "pages_shared_saved": self.alloc.refs_saved,
                "evicted": self.evicted,
                "deferred": self.deferred,
                "requeued": self.queue.requeued,
            }
            if self.prefix is not None:
                paged.update({
                    "prefix_cache": True,
                    "prefix_hits": self.prefix_hits,
                    "prefix_entries": len(self.prefix),
                    "prefix_pages_held": self.prefix.pages_held,
                    "prefix_evictions": self.prefix.evicted,
                    "warm_admits": self.warm_admits,
                    "prefill_runs": self.prefill_runs,
                })
                if self.time_admissions:
                    paged["prefill_p50_ms"] = _p50_ms(self.prefill_times)
                    paged["warm_admit_p50_ms"] = _p50_ms(
                        self.warm_admit_times)
        spec = {}
        if self.speculative:
            k = self.speculative
            spec = {
                "speculative": k,
                "draft_layers": self.draft_layers,
                "spec_rounds": self.spec_rounds,
                # delivered / proposed: the fraction of proposed
                # positions that survived verify — 1/k is the total-
                # rejection floor (the verify sample always lands), 1.0
                # means every draft matched (end-of-sequence clamping
                # is excluded from the denominator, so a perfect draft
                # really scores 1.0)
                "spec_acceptance_rate": round(
                    self.spec_delivered / max(self.spec_proposed, 1),
                    4),
                "spec_tokens_per_round": round(
                    self.spec_delivered / max(self.spec_rounds, 1), 3),
            }
        return {
            "kv": self.kv,
            "kv_hbm_bytes": self.kv_hbm_bytes(),
            **self._mesh_stats(),
            **paged,
            **spec,
            "queue_depth": self.queue.depth(),
            "active_slots": self.active_slots(),
            "num_slots": self.num_slots,
            "chunk_steps": self.chunk_steps,
            "decode_steps": self.decode_steps,
            "tokens_decoded": self.tokens_decoded,
            "tokens_per_s": (round(self.tokens_decoded / elapsed, 2)
                             if elapsed else 0.0),
            "mean_occupancy": (round(self.occupancy_sum
                                     / max(self.decode_steps, 1), 3)),
            "completed": self.completed,
            "expired": self.expired,
            "cfg_pairs": self.cfg_pairs,
            "reaped": self.reaped,
            "previews_requested": self.previews_requested,
            "rejected": self.queue.rejected,
            "decode_compiles": self.decode_traces,
            "prefill_compiles": self.prefill_traces,
            "prefill_buckets": list(self.buckets),
            "harvests": self.harvests,
            "host_round_trips_per_token": round(
                self.harvests / max(self.tokens_decoded, 1), 6),
            # the obs surface: flight-recorder occupancy (retention is
            # the ring capacity, /debug/events serves the contents) and
            # the serve-side profiler state
            "flight_events": len(self.flight),
            "profile_active": self.profile_active(),
            "profiles_taken": self.profiles_taken,
        }
