"""Slot-pool continuous-batching decode engine.

The one-shot path (``cli/gen_dalle.py`` -> ``models.dalle.generate_images``)
pays full compile + prefill + ~1024 sequential decode steps PER REQUEST,
with no batching across requests. This engine is the serving answer: a
fixed ``[num_slots]`` decode batch compiled ONCE, where requests join and
leave every step via masking (the slot-based continuous batching standard
on TPU — PAPERS.md "Ragged Paged Attention", "Serving Gemma on Cloud
TPU"):

  * the KV cache is allocated once for all slots
    (``ops.decode.init_cache`` at batch = num_slots); a freed slot's stale
    rows are dead by construction (the per-slot causal mask only reads
    rows < that slot's position, and admission overwrites the whole slot
    buffer);
  * every decode step advances ALL slots one token through ONE jitted
    program with per-slot positions (``ops.decode.decode_step`` with a
    (num_slots,) ``pos`` vector), per-slot RNG keys, temperature, top-k
    and top-p — idle slots compute masked garbage, the price of a fixed
    shape and zero recompiles;
  * admission batches pending prompts of the same length through one
    ``ops.decode.prefill`` call and scatters the resulting KV rows into
    the slot pool (compiled per (prompt_len, group_size) — bounded by the
    distinct prompt lengths seen, NOT by request count).

Equivalence contract (tests/test_serve.py pins it): for the same params /
prompt / seed / sampling knobs, a slot's emitted image tokens are
IDENTICAL to ``generate_images`` at batch 1 — the engine reuses
``decode_token_embed``/``logits_mask``/``to_logits`` and reimplements only
the per-slot (traced-parameter) forms of the top-k/top-p filters, which
are value-identical to ``top_k_filter``/``top_p_filter``. Per-slot
sampling draws through ``fold_in(request_rng, position)`` exactly as
``generate_images`` does; ``jax.random.categorical`` over one slot's
(vocab,) row equals the batch-1 call with the same key.

Not supported per-request: classifier-free guidance (it doubles the
stream per request; serve a guidance-dedicated engine instead) and padded
prompt masks (requests carry unpadded codes, gen_dalle's default mode).

The engine is deliberately single-threaded and drivable step-by-step
(``step_once``) so tests and the bench can run it deterministically;
``serve.server`` wraps it in a thread for live traffic.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional

import numpy as np

from dalle_pytorch_tpu.serve import scheduler as S


def _sample_slots(logits, pred_pos, keys, temp, topk_k, top_p, cfg):
    """Per-slot sampling: the traced-parameter form of ``generate_images``'s
    ``sample`` (models/dalle.py) — forbidden-position mask, temperature,
    top-k OR nucleus filter, categorical — with every knob a (slots,)
    array instead of a python constant.

    Value-identical to the one-shot path per slot: the top-k threshold is
    the k-th largest logit (what ``lax.top_k(...)[..., -1:]`` returns)
    read off a full descending sort so k can vary per slot; the nucleus
    branch is ``top_p_filter``'s exact math with p broadcast per slot.
    Both filters are computed every step (fixed shape) and selected per
    slot. Returns sampled token ids with the text-vocab offset removed
    for image positions, as ``generate_images`` stores them."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.ops import core

    forbidden = D.logits_mask(cfg)
    lg = jnp.where(jnp.take(forbidden, pred_pos - 1, axis=0),
                   core.neg_inf(logits.dtype), logits)
    lg = lg / temp[:, None]

    sorted_desc = jnp.flip(jnp.sort(lg, axis=-1), axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (topk_k - 1)[:, None], axis=-1)
    by_k = jnp.where(lg < kth, core.neg_inf(lg.dtype), lg)

    probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None]
    thresh = jnp.min(jnp.where(keep_sorted, sorted_desc,
                               jnp.inf).astype(lg.dtype),
                     axis=-1, keepdims=True)
    by_p = jnp.where(lg < thresh, core.neg_inf(lg.dtype), lg)

    lg = jnp.where((top_p > 0)[:, None], by_p, by_k)
    folded = jax.vmap(jax.random.fold_in)(keys, pred_pos)
    raw = jax.vmap(jax.random.categorical)(folded, lg)
    is_image = pred_pos >= cfg.text_seq_len
    return jnp.where(is_image, raw - cfg.num_text_tokens, raw)


class _Slot:
    """Host-side bookkeeping for one slot of the pool."""

    __slots__ = ("handle", "pos", "cur_tok", "emitted", "t_admit")

    def __init__(self, handle: S.RequestHandle, pos: int, cur_tok: int,
                 t_admit: float):
        self.handle = handle
        self.pos = pos
        self.cur_tok = cur_tok
        self.emitted: List[int] = []
        self.t_admit = t_admit


class Engine:
    """The continuous-batching loop. Pulls from a ``scheduler.RequestQueue``,
    fulfils handles (directly, or through ``complete`` — the postprocess
    hand-off) with ``scheduler.Result``s."""

    def __init__(self, params: dict, cfg, queue: S.RequestQueue, *,
                 num_slots: int = 4,
                 complete: Optional[Callable] = None,
                 metrics=None, log_every: int = 0,
                 quantize_cache: bool = False,
                 clock: Callable[[], float] = time.perf_counter):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.ops import decode as decode_ops

        self.params = params
        self.cfg = cfg
        self.queue = queue
        self.num_slots = int(num_slots)
        self.complete = complete
        self.metrics = metrics
        self.log_every = int(log_every)
        self.quantize_cache = bool(quantize_cache)
        self.clock = clock

        S_ = self.num_slots
        self.total_len = cfg.seq_len
        # device state: the slot-pool KV cache lives on device for the
        # engine's whole life; the small per-slot vectors round-trip the
        # host every step (the host collects tokens anyway). Cache dtype
        # follows the embedding table — the dtype that flows into qkv, so
        # the admission scatter matches what prefill allocates (under
        # bf16 params an f32 default would promote the whole decode carry)
        self.cache = decode_ops.init_cache(
            cfg.transformer, S_, self.total_len,
            dtype=params["text_emb"]["w"].dtype,
            quantized=self.quantize_cache)
        self.key_mask = jnp.ones((S_, self.total_len), bool)
        # host state (numpy; fixed shapes so the jit never retraces)
        self.pos = np.zeros((S_,), np.int32)
        self.cur_tok = np.zeros((S_,), np.int32)
        self.rng = np.zeros((S_, 2), np.uint32)
        self.temp = np.ones((S_,), np.float32)
        self.topk_k = np.ones((S_,), np.int32)
        self.top_p = np.zeros((S_,), np.float32)
        self.slots: List[Optional[_Slot]] = [None] * S_

        # counters (stats()/bench_serve read these)
        self.decode_traces = 0          # bumped only while TRACING: the
        self.prefill_traces = 0         # fixed-shape contract keeps it at 1
        self.decode_steps = 0
        self.tokens_decoded = 0
        self.completed = 0
        self.expired = 0
        self.occupancy_sum = 0
        self._t_start = None

        self._decode_fn = jax.jit(self._decode_impl)
        self._prefill_fns: Dict = {}
        self._lock = threading.Lock()   # step_once is not reentrant

    # -- jitted programs ----------------------------------------------------

    def _decode_impl(self, params, cache, cur_tok, pos, keys, temp,
                     topk_k, top_p):
        """One step for ALL slots: embed each slot's current token at its
        own position, advance the stack once, sample each slot's next
        token. Traced exactly once (fixed shapes) — the side-effecting
        counter below proves it."""
        self.decode_traces += 1
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.ops import decode as decode_ops

        x = D.decode_token_embed(params, self.cfg, cur_tok, pos)
        h, cache = decode_ops.decode_step(
            params["transformer"], x, pos, cache,
            cfg=self.cfg.transformer, key_mask=self.key_mask)
        logits = D.to_logits(params, h)
        nxt = _sample_slots(logits, pos + 1, keys, temp, topk_k, top_p,
                            self.cfg)
        return nxt, cache

    def _prefill_fn(self, t0: int, n: int):
        """Admission program for a group of ``n`` same-length prompts:
        batched prefill + scatter of the KV rows into the slot pool +
        each request's FIRST sampled token (position t0, key
        ``fold_in(rng, t0)`` — ``generate_images``'s first_tok). Compiled
        per (t0, n): bounded by distinct prompt lengths, not requests."""
        import jax
        import jax.numpy as jnp
        key = (t0, n)
        if key in self._prefill_fns:
            return self._prefill_fns[key]

        def pre(params, cache, text, slots, keys, temp, topk_k, top_p):
            self.prefill_traces += 1
            from dalle_pytorch_tpu.models import dalle as D
            from dalle_pytorch_tpu.ops import decode as decode_ops

            tokens = D.embed_prompt(params, self.cfg, text)
            h, group = decode_ops.prefill(
                params["transformer"], tokens, cfg=self.cfg.transformer,
                total_len=self.total_len, prompt_mask=None,
                quantize_cache=self.quantize_cache)
            cache = {k: cache[k].at[:, slots].set(group[k]) for k in cache}
            logits = D.to_logits(params, h[:, -1])
            first = _sample_slots(logits,
                                  jnp.full((text.shape[0],), t0, jnp.int32),
                                  keys, temp, topk_k, top_p, self.cfg)
            return first, cache

        fn = jax.jit(pre)
        self._prefill_fns[key] = fn
        return fn

    # -- request lifecycle --------------------------------------------------

    def _finish(self, handle: S.RequestHandle, result: S.Result) -> None:
        if result.status == S.OK and self.complete is not None:
            self.complete(handle, result)
        else:
            handle.fulfill(result)

    def _expire(self, handle: S.RequestHandle, now: float,
                where: str) -> None:
        req = handle.request
        self.expired += 1
        if self.metrics is not None:
            self.metrics.event(**S.structured_event(
                "serve_deadline", request_id=req.request_id, where=where,
                deadline_s=req.deadline_s,
                waited_s=round(now - req.submit_t, 4)))
        self._finish(handle, S.Result(
            status=S.DEADLINE_EXCEEDED, request_id=req.request_id,
            reason=f"deadline_s={req.deadline_s:g} exceeded ({where})",
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _error(self, handle: S.RequestHandle, now: float,
               reason: str) -> None:
        req = handle.request
        if self.metrics is not None:
            self.metrics.event(**S.structured_event(
                "serve_error", request_id=req.request_id, error=reason))
        self._finish(handle, S.Result(
            status=S.ERROR, request_id=req.request_id, reason=reason,
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _admit(self, handles: List[S.RequestHandle], now: float) -> None:
        import jax
        free = [i for i, s in enumerate(self.slots) if s is None]
        assert len(handles) <= len(free)
        groups = defaultdict(list)
        for h in handles:
            # the server's queue validates at submit; a raw queue may
            # not — a prompt the pool can't hold must become a typed
            # error result, never a crash of the serving loop
            n = len(h.request.codes)
            if not 1 <= n <= self.cfg.text_seq_len:
                self._error(h, now, f"invalid prompt length {n} "
                            f"(need 1..{self.cfg.text_seq_len})")
                continue
            groups[n].append(h)
        for t0, group in groups.items():
            idx = free[:len(group)]
            free = free[len(group):]
            text = np.asarray([h.request.codes for h in group], np.int32)
            slots = np.asarray(idx, np.int32)
            for i, h in zip(idx, group):
                req = h.request
                v = self.cfg.total_tokens
                self.rng[i] = np.asarray(
                    jax.random.PRNGKey(req.seed), np.uint32)
                self.temp[i] = np.float32(req.sampling.temperature)
                self.topk_k[i] = max(
                    int((1 - req.sampling.filter_thres) * v), 1)
                self.top_p[i] = np.float32(req.sampling.top_p)
            try:
                # same explicit-transfer discipline as step_once: the
                # admission path's host<->device traffic is device_put/
                # device_get at the site, never implicit conversion
                first, self.cache = self._prefill_fn(t0, len(group))(
                    self.params, self.cache, jax.device_put(text),
                    jax.device_put(slots), jax.device_put(self.rng[idx]),
                    jax.device_put(self.temp[idx]),
                    jax.device_put(self.topk_k[idx]),
                    jax.device_put(self.top_p[idx]))
            except Exception as e:  # noqa: BLE001 — no-hangs contract
                # the group's slots were never assigned (still None), so
                # the pool stays consistent; the group's callers get a
                # typed error instead of hanging on a dead loop
                for h in group:
                    self._error(h, now, f"prefill failed: {e!r}")
                continue
            first = jax.device_get(first)
            for j, (i, h) in enumerate(zip(idx, group)):
                self.pos[i] = t0
                self.cur_tok[i] = first[j]
                self.slots[i] = _Slot(h, t0, int(first[j]), now)

    def _harvest(self, now: float) -> None:
        """Complete slots whose sequence is done; free them."""
        for i, slot in enumerate(self.slots):
            if slot is None or self.pos[i] < self.total_len:
                continue
            req = slot.handle.request
            full = list(req.codes) + slot.emitted
            img_seq = np.asarray(full[-self.cfg.image_seq_len:], np.int32)
            # the completed text span (prompt + sampled text tokens) —
            # generate_images' full[:, :text_seq_len], what CLIP rerank
            # scores (postprocess.py)
            text_seq = np.asarray(full[:self.cfg.text_seq_len], np.int32)
            self.completed += 1
            self._finish(slot.handle, S.Result(
                status=S.OK, request_id=req.request_id, tokens=img_seq,
                text_tokens=text_seq,
                queued_s=round(slot.t_admit - req.submit_t, 6),
                decode_s=round(now - slot.t_admit, 6),
                total_s=round(now - req.submit_t, 6)))
            self.slots[i] = None
            # idle slots park at pos 0: they rewrite their dead row 0
            # instead of scattering past the cache end
            self.pos[i] = 0
            self.cur_tok[i] = 0

    # -- the loop -----------------------------------------------------------

    def active_slots(self) -> int:
        return sum(s is not None for s in self.slots)

    def step_once(self) -> bool:
        """One engine iteration: expire, admit, decode one token on every
        active slot, harvest. Returns True when any work happened.

        Transfer discipline: the steady-state decode body below performs
        its host<->device traffic through EXPLICIT jax.device_put /
        device_get only, so tests can pin the contract with
        ``analysis.guards.no_transfers()`` — an implicit transfer
        sneaking into the hot loop fails tier-1, while the one known,
        intentional round-trip stays visible at its site."""
        import jax
        with self._lock:
            now = self.clock()
            if self._t_start is None:
                self._t_start = now

            # mid-decode deadlines: a slot past its deadline is cancelled
            # before it spends another step
            for i, slot in enumerate(self.slots):
                if slot is None:
                    continue
                dt = slot.handle.request.deadline_t
                if dt is not None and now > dt:
                    self._expire(slot.handle, now, where="decoding")
                    self.slots[i] = None
                    self.pos[i] = 0
                    self.cur_tok[i] = 0

            free = self.num_slots - self.active_slots()
            ready, expired = self.queue.pop_ready(free, now)
            for h in expired:
                self._expire(h, now, where="queued")
            if ready:
                self._admit(ready, now)

            n_active = self.active_slots()
            if n_active == 0:
                return bool(ready or expired)

            # every active slot emits its current token, then advances
            for slot in self.slots:
                if slot is not None:
                    slot.emitted.append(int(slot.cur_tok))
            nxt, self.cache = self._decode_fn(
                self.params, self.cache, jax.device_put(self.cur_tok),
                jax.device_put(self.pos), jax.device_put(self.rng),
                jax.device_put(self.temp), jax.device_put(self.topk_k),
                jax.device_put(self.top_p))
            # jaxlint: disable=JL001 — the ONE intentional per-step
            # round-trip: the host collects each slot's emitted token.
            # ROADMAP (Serving, still open): keep cur_tok/pos on device
            # and fetch emitted tokens asynchronously every K steps.
            nxt = jax.device_get(nxt)
            for i, slot in enumerate(self.slots):
                if slot is None:
                    continue
                self.pos[i] += 1
                self.cur_tok[i] = nxt[i]
                slot.cur_tok = int(nxt[i])
                slot.pos = int(self.pos[i])
            self.decode_steps += 1
            self.tokens_decoded += n_active
            self.occupancy_sum += n_active

            if (self.metrics is not None and self.log_every
                    and self.decode_steps % self.log_every == 0):
                self.metrics.event(event="serve", **self.stats())

            self._harvest(self.clock())
            return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Drive until the queue is empty and every slot is free (tests,
        bench). ``max_steps`` is a runaway guard, not a budget."""
        for _ in range(max_steps):
            busy = self.step_once()
            if not busy and self.queue.depth() == 0 \
                    and self.active_slots() == 0:
                return
        raise RuntimeError(f"engine did not go idle in {max_steps} steps")

    def run(self, stop: threading.Event, idle_sleep_s: float = 0.002):
        """Serving loop for a dedicated thread (serve.server): spin while
        there is work, nap briefly when idle. An exception out of
        ``step_once`` must NOT kill the loop — one bad step would leave
        every queued and future request hanging forever while /healthz
        still answers. Instead the implicated in-slot requests are
        fulfilled with typed ``error`` results, the pool is reset to a
        consistent idle state, and serving continues."""
        while not stop.is_set():
            try:
                busy = self.step_once()
            except Exception as e:  # noqa: BLE001 — no-hangs contract
                # recovery FIRST, observability second: a raising
                # metrics sink must not kill the thread before the
                # in-slot handles are fulfilled
                n = self.fail_active(f"engine step failed: {e!r}")
                if self.metrics is not None:
                    try:
                        self.metrics.event(**S.structured_event(
                            "serve_engine_error", error=repr(e),
                            failed=n))
                    except Exception:   # noqa: BLE001
                        pass
                stop.wait(idle_sleep_s)     # never hot-spin on a
                continue                    # persistent fault
            if not busy and self.queue.depth() == 0 \
                    and self.active_slots() == 0:
                stop.wait(idle_sleep_s)

    def _terminate_active(self, status: str, reason: str) -> int:
        """Fulfil every in-slot request with a typed terminal result and
        reset the pool to idle (slot state may be mid-update on the error
        path, so the only consistent continuation is an empty pool).
        Returns the number terminated."""
        n = 0
        with self._lock:
            now = self.clock()
            for i, slot in enumerate(self.slots):
                if slot is None:
                    continue
                req = slot.handle.request
                slot.handle.fulfill(S.Result(
                    status=status, request_id=req.request_id,
                    reason=reason,
                    queued_s=round(slot.t_admit - req.submit_t, 6),
                    total_s=round(now - req.submit_t, 6)))
                self.slots[i] = None
                n += 1
            self.pos[:] = 0
            self.cur_tok[:] = 0
        return n

    def fail_active(self, reason: str) -> int:
        """Typed ``error`` results for every in-slot request — the
        run-loop's recovery path after an unexpected step failure."""
        return self._terminate_active(S.ERROR, reason)

    def cancel_active(self, reason: str = "server shutdown") -> int:
        """Typed ``cancelled`` results for every in-slot request — the
        shutdown path (the no-hangs contract must cover requests already
        admitted, not just queued ones)."""
        return self._terminate_active(S.CANCELLED, reason)

    # -- observability ------------------------------------------------------

    def stats(self) -> dict:
        elapsed = None if self._t_start is None \
            else max(self.clock() - self._t_start, 1e-9)
        return {
            "queue_depth": self.queue.depth(),
            "active_slots": self.active_slots(),
            "num_slots": self.num_slots,
            "decode_steps": self.decode_steps,
            "tokens_decoded": self.tokens_decoded,
            "tokens_per_s": (round(self.tokens_decoded / elapsed, 2)
                             if elapsed else 0.0),
            "mean_occupancy": (round(self.occupancy_sum
                                     / max(self.decode_steps, 1), 3)),
            "completed": self.completed,
            "expired": self.expired,
            "rejected": self.queue.rejected,
            "decode_compiles": self.decode_traces,
            "prefill_compiles": self.prefill_traces,
        }
