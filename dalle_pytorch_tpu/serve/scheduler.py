"""Request queue with admission control: priorities, deadlines,
backpressure.

The serving contract (docs/SERVING.md) is that overload is STRUCTURED:
a full queue rejects at submit time with a typed error carrying the same
``utils.metrics.structured_event`` record shape the resilience runtime
uses, and a request whose deadline passes — in the queue or mid-decode —
completes with a typed ``deadline_exceeded`` result. Nothing hangs,
nothing is silently dropped; every terminal state is one of
``Result.status``'s enumerated strings, observable both by the caller
(through ``RequestHandle.result``) and post-hoc (through the JSONL
metrics stream).

Ordering is (priority, arrival): lower ``priority`` values run first,
FIFO within a priority class. Deadlines do not reorder the queue — a
deadline is a promise about when a result stops being useful, not a
scheduling hint — they only gate admission to a slot.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dalle_pytorch_tpu.obs import trace as otrace
from dalle_pytorch_tpu.utils.metrics import structured_event

# Result.status values — the full set of terminal request states.
OK = "ok"
REJECTED = "rejected"
DEADLINE_EXCEEDED = "deadline_exceeded"
CANCELLED = "cancelled"
ERROR = "error"


def prefill_buckets(text_seq_len: int) -> Tuple[int, ...]:
    """The default prompt-length buckets: powers of two up to (and always
    including) ``text_seq_len``. Admission pads every prompt up to its
    bucket, so the engine's prefill program compiles once per BUCKET for
    the engine's life — a small fixed set — instead of once per distinct
    prompt length seen (docs/SERVING.md "Prompt-length bucketing")."""
    if text_seq_len < 1:
        raise ValueError(f"text_seq_len must be >= 1, got {text_seq_len}")
    out: List[int] = []
    b = 1
    while b < text_seq_len:
        out.append(b)
        b *= 2
    out.append(text_seq_len)
    return tuple(out)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding a length-``n`` prompt. ``buckets`` must be
    sorted ascending; raises for a prompt no bucket can hold (callers
    validate prompt length before bucketing)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket "
                     f"{buckets[-1]}")


def group_by_bucket(handles: Sequence["RequestHandle"],
                    buckets: Sequence[int]
                    ) -> Dict[int, List["RequestHandle"]]:
    """Bucket-aware admission grouping: handles keyed by the bucket their
    prompt pads up to, preserving pop order within a bucket. One prefill
    dispatch per KEY — bounded by ``len(buckets)``, not by the distinct
    prompt lengths seen."""
    groups: Dict[int, List[RequestHandle]] = defaultdict(list)
    for h in handles:
        groups[bucket_for(len(h.request.codes), buckets)].append(h)
    return groups


class ServeRejected(RuntimeError):
    """Typed submit-time rejection. ``record`` is the structured event
    (kind ``serve_reject``) describing why — the backpressure contract's
    machine-readable half."""

    def __init__(self, record: dict):
        super().__init__(f"{record.get('reason', 'rejected')} "
                         f"(queue_depth={record.get('queue_depth')})")
        self.record = record


class QueueFull(ServeRejected):
    """The bounded queue is at capacity — shed load at the edge instead
    of letting latency grow without bound."""


class InvalidRequest(ServeRejected):
    """The request can never run (empty prompt, or prompt longer than the
    model's text span) — rejected at submit so a malformed request cannot
    reach the engine, let alone take down its decode loop."""


class QueueClosed(ServeRejected):
    """The server is shutting down; a submit racing ``close()`` gets this
    typed reject instead of landing in a queue nobody will ever drain."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs — the same surface ``generate_images``
    exposes (models/dalle.py), carried per slot by the engine."""
    temperature: float = 1.0
    filter_thres: float = 0.5
    top_p: float = 0.0

    def __post_init__(self):
        if self.temperature <= 0:
            raise ValueError(f"temperature must be > 0, got "
                             f"{self.temperature}")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {self.top_p}")


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request: ``codes`` is the (unpadded) prompt token
    ids, exactly what ``generate_images`` takes as one text row.
    ``cfg_scale > 0`` asks for classifier-free guidance — the engine
    admits a cond/uncond slot pair and image tokens sample from
    ``l_u + cfg_scale * (l_c - l_u)``, exactly ``generate_images``'
    ``guidance`` knob (1.0 reduces to conditional sampling but still
    pays the pair; 0, the default, is off). ``tenant`` names the
    admitting tenant for weighted-fair queueing and per-tenant
    accounting — ``""`` (the default) is the anonymous tenant, which
    keeps single-tenant deployments byte-identical to before.

    ``stream`` marks the request as a live token stream: the engine
    pushes every harvested chunk into the handle's attached sink
    (serve/stream.py) as it lands, in addition to the terminal Result.
    ``n_samples > 1`` asks for a best-of-N sample GROUP — the serving
    tier fans the prompt out into N member requests with per-sample
    derived seeds (serve/fanout.py) and re-ranks the finished set by
    CLIP score; the field rides the wire so a gateway/transport hop
    can charge and route the whole group as one unit.
    ``image_seq_len_override`` (0 = off) caps the generated image span
    at fewer tokens than the model's full grid: decode stops once the
    override span is sampled, a train-free short-grid draft that rides
    the existing prefill buckets unchanged."""
    codes: Tuple[int, ...]
    seed: int = 0
    sampling: SamplingParams = SamplingParams()
    priority: int = 0                    # lower runs first
    deadline_s: Optional[float] = None   # relative to submit time
    cfg_scale: float = 0.0               # classifier-free guidance
    tenant: str = ""                     # admitting tenant (gateway)
    stream: bool = False                 # live token sink wanted
    n_samples: int = 1                   # best-of-N group size
    image_seq_len_override: int = 0      # 0 = full grid
    request_id: int = -1                 # assigned by the queue
    submit_t: float = 0.0                # perf_counter, set by the queue

    def __post_init__(self):
        if self.cfg_scale < 0:
            raise ValueError(f"cfg_scale must be >= 0, got "
                             f"{self.cfg_scale}")
        if self.n_samples < 1:
            raise ValueError(f"n_samples must be >= 1, got "
                             f"{self.n_samples}")
        if self.image_seq_len_override < 0:
            raise ValueError(f"image_seq_len_override must be >= 0, "
                             f"got {self.image_seq_len_override}")

    @property
    def deadline_t(self) -> Optional[float]:
        if self.deadline_s is None:
            return None
        return self.submit_t + self.deadline_s

    def to_wire(self, now: float) -> dict:
        """Flat-dict form for the process-isolation IPC (serve/ipc.py
        frames, versions, and checksums it). Two clocks never cross the
        boundary: the deadline ships as the REMAINING budget at send
        time (``perf_counter`` bases differ between processes), and the
        receiver re-anchors it on its own clock. Every field is a JSON
        scalar/list, so the round trip is exact — ints verbatim, floats
        via repr round-tripping — which is what lets a replayed request
        decode bit-identically on a survivor in another process."""
        return {
            "id": int(self.request_id),
            "codes": [int(c) for c in self.codes],
            "seed": int(self.seed),
            "priority": int(self.priority),
            "temperature": float(self.sampling.temperature),
            "filter_thres": float(self.sampling.filter_thres),
            "top_p": float(self.sampling.top_p),
            "deadline_left_s": (None if self.deadline_s is None
                                else max(self.deadline_t - now, 0.0)),
            "cfg_scale": float(self.cfg_scale),
            "tenant": str(self.tenant),
            "stream": bool(self.stream),
            "n_samples": int(self.n_samples),
            "image_seq_len_override": int(self.image_seq_len_override),
        }

    @classmethod
    def from_wire(cls, d: dict, now: float) -> "Request":
        """Inverse of ``to_wire``, validating by construction (the
        ``SamplingParams`` range checks run again on this side — a
        corrupt frame becomes a typed error, never a poisoned engine).
        ``submit_t`` is re-anchored to the receiver's clock."""
        deadline = d["deadline_left_s"]
        return cls(
            codes=tuple(int(c) for c in d["codes"]),
            seed=int(d["seed"]),
            sampling=SamplingParams(
                temperature=float(d["temperature"]),
                filter_thres=float(d["filter_thres"]),
                top_p=float(d["top_p"])),
            priority=int(d["priority"]),
            deadline_s=None if deadline is None else float(deadline),
            # .get: frames from a pre-guidance peer simply decode as
            # unguided instead of failing the whole attach
            cfg_scale=float(d.get("cfg_scale", 0.0)),
            # .get: pre-tenancy frames decode as the anonymous tenant
            tenant=str(d.get("tenant", "")),
            # .get x3: pre-streaming frames decode as plain one-shot
            # full-grid requests — the same tolerance rule as above
            stream=bool(d.get("stream", False)),
            n_samples=int(d.get("n_samples", 1)),
            image_seq_len_override=int(
                d.get("image_seq_len_override", 0)),
            request_id=int(d["id"]),
            submit_t=float(now))


@dataclasses.dataclass
class Result:
    """Terminal state of a request. ``tokens`` is the sampled image-token
    sequence (image ids, no text offset — ``generate_images``'s
    ``img_seq``); ``text_tokens`` is the COMPLETED text span (the prompt
    plus the model-sampled text tokens filling it out to ``text_seq_len``
    — ``generate_images``'s ``full[:, :text_seq_len]``), what CLIP
    rerank scores; ``image`` is filled by the postprocess stage when
    image decoding is enabled. ``weights_version`` names the weight
    generation that produced the tokens (stamped by the engine that
    decoded them) — the rolling-upgrade contract is that same-seed
    tokens are byte-identical PER weights_version, so a caller or a
    replay audit can always tell which generation a result came from."""
    status: str
    request_id: int
    tokens: object = None
    text_tokens: object = None
    image: object = None
    clip_score: Optional[float] = None
    reason: str = ""
    weights_version: str = ""
    queued_s: float = 0.0
    decode_s: float = 0.0
    total_s: float = 0.0
    # the trace summary (obs/trace.py): span timeline aggregated by
    # name + replay edges. Attached by RequestHandle.fulfill from the
    # handle's trace — never crosses the wire itself (a child's spans
    # ride the result frame raw; the parent re-summarizes its merged
    # trace, so the summary always describes the CALLER's timeline)
    trace: Optional[dict] = None
    # best-of-N group assembly (serve/fanout.py): the member Results
    # ranked best-first by CLIP score. Parent-side only — members
    # cross the wire individually; the group is re-assembled wherever
    # the caller's GroupFuture lives, so this never ships in a frame
    samples: Optional[list] = None

    @property
    def ok(self) -> bool:
        return self.status == OK

    def to_wire(self) -> dict:
        """Flat-dict form for the process-isolation IPC. Token arrays
        ship as plain int lists; ``image``/``clip_score`` never cross
        the boundary (the child runs decode only — VAE/CLIP postprocess
        stays in the parent, downstream of the fulfilled handle)."""
        return {
            "id": int(self.request_id),
            "status": str(self.status),
            "tokens": (None if self.tokens is None
                       else [int(t) for t in self.tokens]),
            "text_tokens": (None if self.text_tokens is None
                            else [int(t) for t in self.text_tokens]),
            "reason": str(self.reason),
            "weights_version": str(self.weights_version),
            "queued_s": float(self.queued_s),
            "decode_s": float(self.decode_s),
            "total_s": float(self.total_s),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Result":
        status = str(d["status"])
        if status not in (OK, REJECTED, DEADLINE_EXCEEDED, CANCELLED,
                          ERROR):
            raise ValueError(f"unknown Result.status {status!r}")
        import numpy as np
        toks = d["tokens"]
        text = d["text_tokens"]
        return cls(
            status=status, request_id=int(d["id"]),
            tokens=None if toks is None else np.asarray(
                [int(t) for t in toks], np.int32),
            text_tokens=None if text is None else np.asarray(
                [int(t) for t in text], np.int32),
            reason=str(d["reason"]),
            # .get: frames from a pre-upgrade peer decode as unversioned
            # instead of failing the attach (Request.from_wire's rule)
            weights_version=str(d.get("weights_version", "")),
            queued_s=float(d["queued_s"]),
            decode_s=float(d["decode_s"]),
            total_s=float(d["total_s"]))


class RequestHandle:
    """Future for one request: ``result(timeout)`` blocks until the
    engine/postprocess fulfils it. Always fulfilled with a ``Result`` —
    including rejects and expiries — so callers never hang on overload.

    ``fulfill`` is FIRST-WRITE-WINS: replica failover re-queues a fenced
    replica's in-flight requests for deterministic replay on a survivor,
    so two engines can transiently both believe they own a handle (the
    wedged one waking mid-step, and the replay). The first terminal
    result sticks; a late second fulfil is a no-op, never an overwrite
    of a result the caller may already have read."""

    def __init__(self, request: Request):
        self.request = request
        self._done = threading.Event()
        self._result: Optional[Result] = None
        self._fulfill_lock = threading.Lock()
        # the request's span timeline (obs/trace.py), attached at
        # submit (None for hand-built handles — canaries, raw-queue
        # tests — which trace nothing)
        self.trace: Optional[otrace.Trace] = None
        # arrival order within the priority class, assigned at submit;
        # requeue (eviction/page-defer) re-inserts with the SAME seq so
        # a request never loses its place in line — without this, a
        # large-prompt request deferred on pages would re-enter behind a
        # steady stream of small requests and could starve forever
        self.queue_seq: int = -1
        # the weights generation this request first routed to (set by
        # the replica-set router, parent-side only — it never crosses
        # the wire because reclaim always reads the parent's handle).
        # While pinned, failover replay routes ONLY to a replica on the
        # same version: replayed tokens must be byte-identical to the
        # undisturbed run, and a newer generation's logits are not.
        # None = unpinned (fresh request, or pin released because the
        # version left the fleet entirely — see replica._route).
        self.replay_version: Optional[str] = None
        # weighted-fair queueing tags (WeightedFairQueue): the virtual
        # start/finish stamps assigned ONCE at submit and reused by
        # every requeue — a request's place in the fair order, like its
        # queue_seq, must survive eviction/failover replay unchanged or
        # determinism (and the no-starvation argument) breaks
        self.vstart: Optional[float] = None
        self.vfinish: Optional[float] = None
        # live token sink (serve/stream.py TokenSink), attached by the
        # server when request.stream is set. None for everything else —
        # the engine's harvest feeds it when present and never blocks
        # on it. Parent-side only: a process-isolation stand-in handle
        # has no sink, which is why streaming there is a typed reject.
        self.sink = None

    def done(self) -> bool:
        return self._done.is_set()

    def fulfill(self, result: Result) -> bool:
        with self._fulfill_lock:
            if self._done.is_set():
                return False
            if self.trace is not None and result.trace is None:
                # the ONE summary site: every terminal path (completion,
                # postprocess, expiry, cancellation, failover replay)
                # funnels through fulfill, so the caller always sees
                # the timeline that actually produced its result
                result.trace = self.trace.summary()
            self._result = result
            self._done.set()
        # outside the lock: closing the stream sink can wake a consumer
        # thread that immediately calls back into handle methods — and
        # fulfill is the ONE terminal funnel, so every path (completion,
        # postprocess, expiry, error, cancel) ends the stream exactly
        # once. A sink failure must never lose the result itself.
        if self.sink is not None:
            try:
                self.sink.close(result)
            except Exception:
                pass
        return True

    def result(self, timeout: Optional[float] = None) -> Result:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request.request_id} not done after "
                f"{timeout}s (still queued or decoding)")
        return self._result

    def to_wire(self, now: float) -> dict:
        """The request's wire form plus the handle-level ``queue_seq`` —
        the original arrival position MUST survive the process boundary,
        or a request reclaimed from a dead child and replayed would lose
        its no-starvation guarantee (``requeue`` re-enters at
        ``queue_seq``). The trace identity (id + attempt) rides along so
        the child's span records carry the SAME trace_id the caller's
        timeline is keyed by."""
        d = {**self.request.to_wire(now), "seq": int(self.queue_seq)}
        if self.trace is not None:
            d["trace_id"] = self.trace.trace_id
            d["attempt"] = int(self.trace.attempt)
        return d

    @classmethod
    def from_wire(cls, d: dict, now: float) -> "RequestHandle":
        """Child-side reconstruction: a LOCAL stand-in handle whose
        fulfillment the worker observes and ships back as a result
        frame — the parent's real handle (the caller's future) never
        leaves the parent process. The stand-in gets its own trace
        under the wire's trace_id/attempt: its spans ship back with
        the result and merge into the parent trace (.get: frames from
        a pre-tracing peer simply decode traceless)."""
        handle = cls(Request.from_wire(d, now))
        handle.queue_seq = int(d["seq"])
        tid = d.get("trace_id")
        if tid is not None:
            otrace.attach(handle, handle.request.request_id, now,
                          trace_id=str(tid),
                          attempt=int(d.get("attempt", 0)))
        return handle


class RequestQueue:
    """Bounded, thread-safe priority queue.

    ``submit`` raises ``QueueFull`` past ``max_depth`` (the structured
    reject), ``InvalidRequest`` for a prompt the engine could never run
    (empty, or longer than ``max_prompt_len`` when one is set — the
    server sets it to ``cfg.text_seq_len``), and ``QueueClosed`` after
    ``close()``; ``pop_ready`` hands the engine up to ``n`` admissible
    requests in (priority, arrival) order, separating out entries whose
    deadline already passed so the engine can fulfil them as
    ``deadline_exceeded`` without spending a slot."""

    def __init__(self, max_depth: int = 64,
                 max_prompt_len: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 on_event=None):
        self.max_depth = int(max_depth)
        self.max_prompt_len = max_prompt_len
        self.clock = clock
        self.on_event = on_event
        self._heap: list = []
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._closed = False
        self._drained = False
        self.submitted = 0
        self.rejected = 0
        self.requeued = 0

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def _order_key(self, handle: RequestHandle):
        """The heap's primary sort key for one handle, computed under
        ``_lock``. The base queue orders by priority alone (FIFO within
        a class via ``queue_seq``, the tuple's second element);
        ``WeightedFairQueue`` overrides this with (priority, virtual
        finish time). MUST be stable across requeues of the same handle
        — a request's place in line is part of the replay contract."""
        return handle.request.priority

    def _on_pop(self, handle: RequestHandle) -> None:
        """Hook called under ``_lock`` for each handle handed to the
        engine by ``pop_ready`` — ``WeightedFairQueue`` advances the
        system virtual clock here. Base queue: no-op."""

    def close(self) -> None:
        """Gate further ``submit``s (typed ``QueueClosed``). Set BEFORE
        the shutdown drain so a submit racing ``close()`` cannot land in
        the queue after the drain and leave its caller blocked."""
        with self._lock:
            self._closed = True

    def _reject(self, exc_type, **fields):
        self.rejected += 1
        record = structured_event("serve_reject", **fields)
        if self.on_event is not None:
            self.on_event(record)
        raise exc_type(record)

    def submit(self, request: Request, sink=None) -> RequestHandle:
        """``sink`` (serve/stream.py TokenSink) must be attached HERE,
        under the same lock that publishes the handle to the heap — an
        attach after submit returns would race the engine thread, which
        can pop, prefill, and harvest the first chunk before the caller
        runs again, silently losing the stream's opening tokens."""
        now = self.clock()
        with self._lock:
            if self._closed:
                self._reject(QueueClosed, reason="queue_closed",
                             queue_depth=len(self._heap),
                             priority=request.priority)
            n_codes = len(request.codes)
            if n_codes == 0 or (self.max_prompt_len is not None
                                and n_codes > self.max_prompt_len):
                self._reject(InvalidRequest, reason="invalid_prompt",
                             prompt_len=n_codes,
                             max_prompt_len=self.max_prompt_len,
                             queue_depth=len(self._heap),
                             priority=request.priority)
            if len(self._heap) >= self.max_depth:
                self._reject(QueueFull, reason="queue_full",
                             queue_depth=len(self._heap),
                             max_depth=self.max_depth,
                             priority=request.priority)
            rid = self.submitted
            self.submitted += 1
            request = dataclasses.replace(request, request_id=rid,
                                          submit_t=now)
            handle = RequestHandle(request)
            handle.queue_seq = next(self._seq)
            handle.sink = sink
            # every submitted request is traced (obs/trace.py): the
            # zero-duration submit marker anchors the tiling timeline
            # at the exact instant the caller's latency clock starts
            otrace.attach(handle, rid, now).span(
                "submit", now, priority=int(request.priority),
                prompt_len=len(request.codes))
            heapq.heappush(self._heap, (self._order_key(handle),
                                        handle.queue_seq, handle))
            return handle

    def requeue(self, handle: RequestHandle, count: bool = True) -> None:
        """Push an already-admitted request BACK into the queue — the
        paged engine's eviction/page-backpressure path and replica
        failover's reclaim path (a victim's pages are freed, or its dead
        replica fenced, and the request re-enters the line, never
        dropped). The handle and its original ``submit_t`` are
        preserved, so the caller's future stays live and latency
        accounting covers both attempts. Deliberately not subject to
        ``max_depth`` (the request already passed admission once;
        shedding it here would turn backpressure into a silent drop)
        nor to ``close()`` gating. It re-enters at its ORIGINAL arrival
        position (``queue_seq``), not the back of its priority class:
        together with the engine's head-of-line page reservation this
        is what makes 'no request starves forever' true — later-
        arriving requests can never leapfrog a page-deferred one
        indefinitely. A requeue landing AFTER the shutdown drain
        fulfils the handle as ``cancelled`` on the spot: the heap is
        dead by then, nobody would ever pop it, and leaving it there
        would strand the caller in ``result()``.

        ``count=False`` is the replica-set router's hand-off into a
        replica's private queue — a normal dispatch, not backpressure —
        so ``requeued`` keeps meaning evictions/deferrals/failovers."""
        with self._lock:
            if self._drained:
                handle.fulfill(Result(
                    status=CANCELLED,
                    request_id=handle.request.request_id,
                    reason="server shutdown"))
                return
            if any(entry[2] is handle for entry in self._heap):
                # already back in line: the failover reclaim sweep and a
                # fenced engine waking from a wedge can both try to
                # return the same handle — a double entry would admit
                # (and decode) the request twice
                return
            if count:
                self.requeued += 1
            heapq.heappush(self._heap, (self._order_key(handle),
                                        handle.queue_seq, handle))

    def pop_ready(self, n: int,
                  now: Optional[float] = None
                  ) -> Tuple[List[RequestHandle], List[RequestHandle]]:
        """Up to ``n`` (ready, expired) handles. EVERY deadline-expired
        queued entry is reaped on every call — including ``n == 0`` (a
        full slot pool): a dead entry must neither hold queue capacity
        against fresh submissions nor wait for a free slot to receive its
        typed deadline_exceeded result."""
        if now is None:
            now = self.clock()
        ready: List[RequestHandle] = []
        dead: list = []
        with self._lock:
            keep = []
            for entry in self._heap:          # reap expired everywhere
                dt = entry[2].request.deadline_t
                (dead if dt is not None and now > dt
                 else keep).append(entry)
            if dead:
                heapq.heapify(keep)
                self._heap = keep
            while self._heap and len(ready) < n:
                popped = heapq.heappop(self._heap)[2]
                self._on_pop(popped)
                ready.append(popped)
        return ready, [e[2] for e in dead]

    def pending_prompt_lens(self) -> List[int]:
        """Prompt lengths of everything currently queued — the engine's
        ``compile_pending`` probe (is any queued prompt's bucket still
        uncompiled?) without reaching into the heap layout."""
        with self._lock:
            return [len(entry[2].request.codes) for entry in self._heap]

    def pending_prompt_codes(self) -> List[Tuple[Tuple[int, ...], float]]:
        """(codes, cfg_scale) of everything currently queued — the
        prefix-cache half of the engine's ``compile_pending`` probe
        (could a queued prompt be the first WARM admission, whose
        program has its own one-time compile?)."""
        with self._lock:
            return [(entry[2].request.codes, entry[2].request.cfg_scale)
                    for entry in self._heap]

    def drain(self) -> List[RequestHandle]:
        """Remove and return everything still queued (shutdown path — the
        server fulfils them as ``cancelled``). After the drain the heap
        is dead: a late ``requeue`` (e.g. an engine thread that outlived
        ``close()``'s join timeout evicting a victim) is fulfilled as
        ``cancelled`` instead of being stranded."""
        with self._lock:
            self._drained = True
            out = [h for _, _, h in self._heap]
            self._heap.clear()
        return out


class WeightedFairQueue(RequestQueue):
    """Start-time fair queueing (SFQ) across tenants, generalizing the
    base queue's arrival-position machinery to per-tenant VIRTUAL time.

    Each tenant ``i`` with weight ``w_i`` keeps a running finish tag;
    a request costing ``c`` (default 1.0 — fair in requests; pass
    ``cost_fn`` for fair-in-image-tokens) is stamped at submit with

        vstart  = max(V, F_i)          # V = system virtual time
        vfinish = vstart + c / w_i     # F_i := vfinish

    and the heap drains by (priority, vfinish, queue_seq): strict
    priority classes still dominate (the base queue's contract), and
    WITHIN a class tenants share throughput in proportion to their
    weights — a weight-2 tenant's tags advance half as fast as a
    weight-1 tenant's, so under saturation it drains twice the work.
    ``V`` advances to the popped request's vstart, and the ``max(V,
    F_i)`` clamp is both fairness directions at once: a tenant idle
    while others ran resumes at ``V`` (no banked credit from the past),
    and a tenant whose backlog pushed ``F_i`` far ahead of ``V`` owes
    nothing once it drains — next submit after ``V`` catches up starts
    at ``V``. No permanent debt, no permanent credit.

    Tags are stamped ONCE (cached on the handle) so a requeue —
    eviction, page-deferral, failover replay — re-enters at the
    request's ORIGINAL virtual position, exactly as ``queue_seq``
    preserves arrival order in the base queue. Determinism of replay
    and the no-starvation argument are inherited unchanged."""

    def __init__(self, max_depth: int = 64,
                 max_prompt_len: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 on_event=None,
                 weight_of: Optional[Callable[[str], float]] = None,
                 cost_fn: Optional[Callable[[Request], float]] = None):
        super().__init__(max_depth=max_depth,
                         max_prompt_len=max_prompt_len,
                         clock=clock, on_event=on_event)
        self.weight_of = weight_of if weight_of is not None \
            else (lambda tenant: 1.0)
        self.cost_fn = cost_fn if cost_fn is not None \
            else (lambda request: 1.0)
        self._vtime = 0.0
        self._ftime: Dict[str, float] = {}

    def _order_key(self, handle: RequestHandle):
        if handle.vfinish is None:       # stamp once, at first insert
            tenant = handle.request.tenant
            weight = max(float(self.weight_of(tenant)), 1e-9)
            vstart = max(self._vtime, self._ftime.get(tenant, 0.0))
            handle.vstart = vstart
            handle.vfinish = vstart + \
                float(self.cost_fn(handle.request)) / weight
            self._ftime[tenant] = handle.vfinish
        return (handle.request.priority, handle.vfinish)

    def _on_pop(self, handle: RequestHandle) -> None:
        if handle.vstart is not None:
            self._vtime = max(self._vtime, handle.vstart)

    def virtual_time(self) -> float:
        with self._lock:
            return self._vtime

    def finish_tag(self, tenant: str) -> float:
        """The tenant's last virtual finish tag (0.0 if never seen) —
        the observability hook the starvation tests pin: a tag at or
        below ``virtual_time()`` means the tenant carries no debt."""
        with self._lock:
            return self._ftime.get(tenant, 0.0)
