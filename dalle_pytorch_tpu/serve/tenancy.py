"""Tenant model for the gateway tier: identity, quotas, fair shares.

A TENANT is the unit of isolation at the front door. Each one carries:

  * an API key — verified constant-time (serve/auth.py) at every
    submit; an unknown or wrong key is a typed 401, never a silent
    default tenant;
  * token buckets — ``rps`` (requests/s) and ``image_tokens_per_s``
    (decode work/s): the cheap, instantaneous half of isolation. A
    bucket refusal is a typed 429 carrying ``retry_after_s`` — the
    degradation contract's "abusive tenant exhausts only its own
    quota" is enforced here, before the shared queue sees the request;
  * a page budget — ``max_pages`` caps the tenant's in-flight mapped
    KV pages FLEET-WIDE (reserved at admission, released at the
    terminal fulfil): rate limits bound arrival, the page budget
    bounds residency, and only both together bound HBM;
  * a weight — its share of the fair queue (scheduler.py's
    ``WeightedFairQueue``) under saturation;
  * an SLO tier — maps to the hedge threshold (gateway.py): how long a
    request may sit un-fulfilled before it is speculatively re-routed
    to a second cell.

The table hot-reloads (``reload``): bucket levels and in-flight page
counts survive for tenants that persist across the reload, so an
operator edit cannot be used to wash away a tenant's spent budget.

Module-level imports are jax-free (the serve package's lazy-import
discipline) — the gateway's admission path never touches a device.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, List, Optional

from dalle_pytorch_tpu.serve import auth
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.utils.metrics import structured_event

# SLO tiers: tier name -> default hedge threshold in seconds. A request
# un-fulfilled past the threshold gets a speculative duplicate on a
# second cell (gateway.py "hedged sends"); ``None`` never hedges.
TIERS: Dict[str, Optional[float]] = {
    "gold": 2.0,
    "silver": 8.0,
    "bronze": None,
}


class AuthError(S.ServeRejected):
    """Typed authentication failure (HTTP 401): unknown API key, or a
    key that fails the constant-time compare. Carries the standard
    structured-event record; the gateway HTTP facade maps it to 401."""


class TenantThrottled(S.ServeRejected):
    """Typed per-tenant quota refusal (HTTP 429). ``record`` is a
    ``tenant_throttled`` structured event with the tenant, which quota
    tripped (``rps`` / ``image_tokens`` / ``pages``), and
    ``retry_after_s`` — the machine-readable half of the degradation
    contract (docs/SERVING.md "Gateway tier")."""

    @property
    def retry_after_s(self) -> float:
        return float(self.record.get("retry_after_s", 0.0))


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's configured identity and limits, as loaded from the
    ``--tenants`` JSON. Zero for a rate/budget means UNLIMITED — the
    single-operator dev deployment is a one-tenant table with zeros."""
    name: str
    key: str = ""
    weight: float = 1.0
    rps: float = 0.0                  # requests per second (0 = no cap)
    image_tokens_per_s: float = 0.0   # decode work per second
    max_pages: int = 0                # fleet-wide in-flight page cap
    tier: str = "bronze"
    hedge_s: Optional[float] = None   # overrides the tier default

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be "
                             f"> 0, got {self.weight}")
        if self.tier not in TIERS:
            raise ValueError(f"tenant {self.name!r}: unknown tier "
                             f"{self.tier!r} (have {sorted(TIERS)})")

    @property
    def hedge_after_s(self) -> Optional[float]:
        return self.hedge_s if self.hedge_s is not None \
            else TIERS[self.tier]

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        return cls(
            name=str(d["name"]),
            key=str(d.get("key", "")),
            weight=float(d.get("weight", 1.0)),
            rps=float(d.get("rps", 0.0)),
            image_tokens_per_s=float(d.get("image_tokens_per_s", 0.0)),
            max_pages=int(d.get("max_pages", 0)),
            tier=str(d.get("tier", "bronze")),
            hedge_s=(None if d.get("hedge_s") is None
                     else float(d["hedge_s"])))


class TokenBucket:
    """Classic token bucket: capacity ``burst``, refilled at ``rate``
    per second. ``rate <= 0`` disables the limit entirely. ``take``
    returns the retry-after in seconds — 0.0 means the tokens were
    granted. Not thread-safe on its own; TenantTable's lock serializes
    access."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        # default burst = 1s of rate, but never below one whole token
        # (a rate of 0.5/s must still admit a single request at once)
        self.burst = float(burst) if burst is not None \
            else max(self.rate, 1.0)
        self.clock = clock
        self.level = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        self.level = min(self.burst,
                         self.level + (now - self._last) * self.rate)
        self._last = now

    def take(self, amount: float = 1.0) -> float:
        """Try to take ``amount`` tokens. Returns 0.0 on success, else
        the seconds until the bucket will hold ``amount`` again — the
        429's ``Retry-After``. A refusal takes nothing (no partial
        spend: a throttled request costs the tenant zero budget)."""
        if self.rate <= 0:
            return 0.0
        now = self.clock()
        self._refill(now)
        if self.level >= amount:
            self.level -= amount
            return 0.0
        return (amount - self.level) / self.rate


class TenantState:
    """One tenant's RUNTIME ledger: buckets, in-flight pages, counters.
    Kept separate from the frozen spec so ``reload`` can swap specs
    while the ledger — spent budget, live reservations — persists."""

    def __init__(self, spec: TenantSpec,
                 clock: Callable[[], float] = time.monotonic):
        self.spec = spec
        self.req_bucket = TokenBucket(spec.rps, clock=clock)
        self.tok_bucket = TokenBucket(
            spec.image_tokens_per_s,
            # decode-work bursts are lumpy (one request = hundreds of
            # image tokens): allow at least one full image per burst
            burst=max(spec.image_tokens_per_s, 1024.0), clock=clock)
        self.pages_in_flight = 0
        self.admitted = 0
        self.throttled = 0
        self.completed = 0

    def rebind(self, spec: TenantSpec) -> None:
        """Hot-reload: adopt the new spec's limits without resetting
        the ledger. Bucket LEVELS carry over (clamped to the new
        burst); rates take effect immediately."""
        self.spec = spec
        self.req_bucket.rate = spec.rps
        self.req_bucket.burst = max(spec.rps, 1.0)
        self.req_bucket.level = min(self.req_bucket.level,
                                    self.req_bucket.burst)
        self.tok_bucket.rate = spec.image_tokens_per_s
        self.tok_bucket.burst = max(spec.image_tokens_per_s, 1024.0)
        self.tok_bucket.level = min(self.tok_bucket.level,
                                    self.tok_bucket.burst)


class TenantTable:
    """The gateway's tenant registry: authentication, admission-time
    quota checks, page-budget reservations, WFQ weights. Thread-safe —
    the gateway's HTTP threads and pump thread share it."""

    def __init__(self, specs: List[TenantSpec],
                 clock: Callable[[], float] = time.monotonic,
                 on_event=None):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in {names}")
        self.clock = clock
        self.on_event = on_event
        self._lock = threading.Lock()
        self._states: Dict[str, TenantState] = {
            s.name: TenantState(s, clock=clock) for s in specs}
        self.reloads = 0

    # -- construction -------------------------------------------------

    @classmethod
    def from_json(cls, data, **kw) -> "TenantTable":
        """Build from the ``--tenants`` JSON shape: either a bare list
        of tenant dicts or ``{"tenants": [...]}``."""
        if isinstance(data, dict):
            data = data.get("tenants", [])
        if not isinstance(data, list):
            raise ValueError("tenants JSON must be a list or "
                             "{'tenants': [...]}")
        return cls([TenantSpec.from_dict(d) for d in data], **kw)

    @classmethod
    def from_file(cls, path: str, **kw) -> "TenantTable":
        with open(path) as f:
            return cls.from_json(json.load(f), **kw)

    # -- introspection ------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._states)

    def spec(self, name: str) -> TenantSpec:
        with self._lock:
            return self._states[name].spec

    def weight_of(self, name: str) -> float:
        """WFQ weight lookup (scheduler.WeightedFairQueue's
        ``weight_of``). Unknown names — e.g. the anonymous tenant on a
        table that never defined one — weigh 1.0."""
        with self._lock:
            st = self._states.get(name)
            return st.spec.weight if st is not None else 1.0

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {name: {
                "weight": st.spec.weight,
                "tier": st.spec.tier,
                "admitted": st.admitted,
                "throttled": st.throttled,
                "completed": st.completed,
                "pages_in_flight": st.pages_in_flight,
                "max_pages": st.spec.max_pages,
            } for name, st in self._states.items()}

    # -- the admission path -------------------------------------------

    def _event(self, kind: str, **fields) -> dict:
        record = structured_event(kind, **fields)
        if self.on_event is not None:
            self.on_event(record)
        return record

    def authenticate(self, api_key: str) -> TenantSpec:
        """Map an API key to its tenant, constant-time per candidate.
        A tenant with an EMPTY configured key is open (matches the
        empty api_key — dev tables); any other mismatch is a typed
        ``AuthError``. Scanning all tenants (no early exit on a name
        hint) keeps the caller's key the only input."""
        with self._lock:
            for st in self._states.values():
                key = st.spec.key
                if (key == "" and api_key == "") or \
                        auth.check_token(api_key, key):
                    return st.spec
        raise AuthError(self._event(
            "gateway_auth_failed", reason="unknown_api_key"))

    def admit(self, tenant: str, *, image_tokens: int,
              pages: int) -> None:
        """All-or-nothing admission charge for one request: request
        bucket, image-token bucket, and the page budget, checked in
        that order with NO partial spend (a pages refusal refunds the
        bucket takes). Raises ``TenantThrottled`` (typed 429) naming
        the quota that tripped."""
        with self._lock:
            st = self._states.get(tenant)
            if st is None:
                raise AuthError(self._event(
                    "gateway_auth_failed", reason="unknown_tenant",
                    tenant=tenant))
            retry = st.req_bucket.take(1.0)
            if retry > 0.0:
                st.throttled += 1
                raise TenantThrottled(self._event(
                    "tenant_throttled", tenant=tenant, quota="rps",
                    retry_after_s=round(retry, 4)))
            retry = st.tok_bucket.take(float(image_tokens))
            if retry > 0.0:
                st.req_bucket.level += 1.0     # refund the first take
                st.throttled += 1
                raise TenantThrottled(self._event(
                    "tenant_throttled", tenant=tenant,
                    quota="image_tokens",
                    retry_after_s=round(retry, 4)))
            if st.spec.max_pages > 0 and \
                    st.pages_in_flight + pages > st.spec.max_pages:
                st.req_bucket.level += 1.0
                st.tok_bucket.level += float(image_tokens)
                st.throttled += 1
                raise TenantThrottled(self._event(
                    "tenant_throttled", tenant=tenant, quota="pages",
                    pages_in_flight=st.pages_in_flight,
                    requested=pages, max_pages=st.spec.max_pages,
                    # pages free as flights retire; one request-time is
                    # the honest "try again" horizon we can promise
                    retry_after_s=1.0))
            st.pages_in_flight += pages
            st.admitted += 1

    def release(self, tenant: str, *, pages: int,
                completed: bool = True) -> None:
        """Return a terminal request's page reservation. Idempotence is
        the CALLER's job (the gateway releases exactly once per flight,
        keyed by request id); the floor clamp here only guards against
        a release racing a reload that dropped and re-added the
        tenant."""
        with self._lock:
            st = self._states.get(tenant)
            if st is None:
                return
            st.pages_in_flight = max(0, st.pages_in_flight - pages)
            if completed:
                st.completed += 1

    # -- hot reload ---------------------------------------------------

    def reload(self, data) -> dict:
        """Swap in a new tenant list (the authenticated admin
        endpoint's hot path). Persisting tenants keep their runtime
        ledger (``TenantState.rebind``); new tenants start fresh;
        removed tenants' in-flight work completes under the gateway's
        per-flight bookkeeping but no new work is admitted. Returns a
        summary event record."""
        if isinstance(data, dict):
            data = data.get("tenants", [])
        specs = [TenantSpec.from_dict(d) for d in data]
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names in {names}")
        with self._lock:
            old = set(self._states)
            states: Dict[str, TenantState] = {}
            for spec in specs:
                st = self._states.get(spec.name)
                if st is not None:
                    st.rebind(spec)
                else:
                    st = TenantState(spec, clock=self.clock)
                states[spec.name] = st
            self._states = states
            self.reloads += 1
            added = sorted(set(names) - old)
            removed = sorted(old - set(names))
        return self._event("gateway_tenants_reloaded",
                           tenants=sorted(names), added=added,
                           removed=removed)
