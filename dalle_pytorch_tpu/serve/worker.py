"""Child-process engine worker — the other end of ``serve/ipc.py``.

``worker_main`` is the spawn entrypoint one process-isolated replica
runs: build a private ``Engine`` (own jax client, pinned to this
replica's device), then loop — drain parent frames, step the engine,
ship completed results and heartbeat snapshots back. The worker holds
no authority: every request it runs also lives in the parent's shadow
bookkeeping, so this process can die AT ANY INSTRUCTION — SIGKILL,
SIGSEGV, OOM — and the supervisor replays its open work byte-identically
on a survivor. The invariants the worker does own:

  * **Results and the counters that count them ride the same frame.**
    A completion is shipped in a harvest frame whose snapshot already
    includes it; the parent absorbs results before the snapshot. The
    prefix of frames that survives a mid-write kill is therefore always
    a consistent state (see ipc.py's module docstring).
  * **A dead parent means exit, not a leak.** Every pipe read/write
    and every idle nap goes through the connection; when the parent
    dies the pipe EOFs/EPIPEs and the worker ``os._exit``\\ s — no
    orphaned interpreters pinning devices after a parent crash.
  * **Local handles are stand-ins.** Admitted requests become child-
    local ``RequestHandle``\\ s (same request_id/queue_seq — replay
    identity survives the boundary); the engine fulfils them locally
    and the worker observes+ships the terminal result. The caller's
    real future never leaves the parent.
  * **The RSS watchdog dies loudly.** With ``rss_limit_mb`` set, the
    worker checks its real RSS (/proc/self/statm) every iteration and
    ``os._exit(137)``\\ s past the limit — the container OOM-kill
    convention, and exactly the abrupt no-goodbye death the supervisor
    must handle from a kernel OOM killer.
  * **Known compiles announce themselves.** A cold decode program or
    prefill bucket blocks this loop for seconds with no frames; the
    worker sends a compiling=True heartbeat BEFORE such a step
    (``Engine.compile_pending``), so the parent's hang deadline doesn't
    read warm-up as a wedge and hard-kill a healthy child.
"""

from __future__ import annotations

import os
import time
from typing import Dict

from dalle_pytorch_tpu.serve import ipc
from dalle_pytorch_tpu.serve import scheduler as S

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_mb() -> int:
    """Resident set size in MiB — /proc on Linux; elsewhere, the
    ru_maxrss (PEAK, the best portable stand-in) with the platform's
    units: bytes on macOS, KiB on the rest."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE // (1 << 20)
    except (OSError, IndexError, ValueError):
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak >> 20 if sys.platform == "darwin" else peak >> 10


def worker_main(spec: dict, conn) -> None:
    """Spawn entrypoint (``multiprocessing`` 'spawn' context — never
    fork a live jax runtime). Exit codes are part of the protocol:
    0 clean (fence/shutdown), 1 crash (after a best-effort CRASH
    frame), 3 parent-gone, 137 RSS watchdog. Signals show up as
    negative exitcodes for the parent to decode."""
    try:
        _run(spec, conn)
    except (EOFError, BrokenPipeError, ConnectionResetError):
        os._exit(3)         # parent died: exit now, leak nothing
    except MemoryError:
        os._exit(ipc.OOM_EXIT)
    except BaseException as e:  # noqa: BLE001 — ship the reason, then die
        try:
            conn.send_bytes(ipc.encode_frame(ipc.CRASH,
                                             {"error": repr(e)}))
        except Exception:   # noqa: BLE001 — the pipe may be gone too
            pass
        os._exit(1)
    os._exit(0)


def _run(spec: dict, conn) -> None:
    from dalle_pytorch_tpu.resilience import faults

    # the parent decides which plan (if any) this child gets — NOT the
    # env var: fire-once for hard kills must outlive the child, so
    # faults.child_plan_for hands a plan to a replica's first spawn
    # only and a restarted child comes up clean
    if spec.get("faults"):
        faults.activate(faults.FaultPlan(**spec["faults"]))
    rss_limit = int(spec.get("rss_limit_mb") or 0)
    index = int(spec["index"])

    import jax

    from dalle_pytorch_tpu.serve.engine import Engine

    devices = jax.devices()
    device = (devices[int(spec["device_index"]) % len(devices)]
              if spec.get("place") else None)
    params = spec["params"]
    if device is None:
        # Engine device_puts params itself when placed; unplaced, do it
        # here so the numpy pytree isn't re-uploaded every jit call
        params = jax.device_put(params)
    queue = S.RequestQueue(max_depth=1 << 30, clock=time.perf_counter)
    engine = Engine(params, spec["cfg"], queue, complete=None,
                    clock=time.perf_counter, device=device,
                    **spec["engine_kwargs"])

    open_handles: Dict[int, S.RequestHandle] = {}
    conn.send_bytes(ipc.encode_frame(
        ipc.READY, {"pid": os.getpid(), "device": str(device),
                    "rss_mb": rss_mb()}))

    hb_interval = float(spec.get("heartbeat_interval_s", 0.05))
    idle_sleep = float(spec.get("idle_sleep_s", 0.002))
    last_hb = 0.0

    def send_snapshot(kind: str, results=None,
                      compiling: bool = False) -> None:
        nonlocal last_hb
        chunks = engine.decode_steps // engine.chunk_steps
        snap = ipc.engine_snapshot(engine, chunks, rss_mb(), compiling)
        payload = {"snap": snap}
        if results is not None:
            payload["results"] = results
        conn.send_bytes(ipc.encode_frame(kind, payload))
        last_hb = time.perf_counter()

    while True:
        # 1. parent frames (admission + control). recv_bytes raising
        # EOFError here IS the parent-death path worker_main handles.
        while conn.poll(0):
            kind, payload = ipc.decode_frame(conn.recv_bytes())
            if kind == ipc.ADMIT:
                now = time.perf_counter()
                for d in payload["requests"]:
                    h = S.RequestHandle.from_wire(d, now)
                    open_handles[h.request.request_id] = h
                    # requeue, not submit: the handle keeps the parent-
                    # assigned request_id and arrival seq — replay
                    # identity and ordering survive the boundary
                    queue.requeue(h, count=False)
            elif kind == ipc.FENCE:
                engine.fence()
                conn.send_bytes(ipc.encode_frame(
                    ipc.BYE, {"reason": "fenced"}))
                return
            elif kind == ipc.SHUTDOWN:
                engine.cancel_active("server shutdown")
                for h in queue.drain():
                    h.fulfill(S.Result(
                        status=S.CANCELLED,
                        request_id=h.request.request_id,
                        reason="server shutdown"))
                conn.send_bytes(ipc.encode_frame(
                    ipc.BYE, {"reason": "shutdown"}))
                return
            elif kind == ipc.STATS_REQ:
                conn.send_bytes(ipc.encode_frame(
                    ipc.STATS, {"stats": engine.stats()}))
            else:
                raise ipc.IPCError(
                    f"unexpected frame kind {kind!r} from parent")

        chunks = engine.decode_steps // engine.chunk_steps
        # the soft catalog (crash raises -> CRASH frame + exit 1; hang
        # sleeps -> missed heartbeats -> the parent hard-kills) AND the
        # hard catalog (real self-SIGKILL/SIGSEGV, OOM against the
        # watchdog, a corrupt frame) both run here, making every serve
        # fault process-drivable
        faults.on_replica_chunk(index, chunks)
        faults.on_worker_chunk(index, chunks,
                               emit_frame=conn.send_bytes,
                               rss_limit_mb=rss_limit, rss_mb=rss_mb)

        # 2. RSS watchdog: die the way a container memory kill does —
        # abruptly, with no goodbye frame, exit 137
        if rss_limit and rss_mb() > rss_limit:
            os._exit(ipc.OOM_EXIT)

        # 3. announce a known-blocking compile BEFORE entering it
        if engine.compile_pending():
            send_snapshot(ipc.HEARTBEAT, compiling=True)

        busy = engine.step_once()

        # 4. ship completions. Batched under the pipe's atomic-write
        # size; ONLY the final batch carries the snapshot, because the
        # snapshot counts every completion in the sweep — a counter
        # must never arrive ahead of the result it counted.
        done = [rid for rid, h in open_handles.items() if h.done()]
        if done:
            wires = [open_handles.pop(rid).result(timeout=0).to_wire()
                     for rid in done]
            for i in range(0, len(wires), ipc.HARVEST_BATCH):
                batch = wires[i:i + ipc.HARVEST_BATCH]
                if i + ipc.HARVEST_BATCH >= len(wires):
                    send_snapshot(ipc.HARVEST, results=batch)
                else:
                    conn.send_bytes(ipc.encode_frame(
                        ipc.HARVEST, {"results": batch, "snap": None}))
        elif time.perf_counter() - last_hb >= hb_interval:
            send_snapshot(ipc.HEARTBEAT)

        # 5. idle nap ON THE PIPE: wakes early for new admissions and
        # notices a dead parent even with nothing to do
        if not busy and engine.idle():
            conn.poll(idle_sleep)
