"""Child-process engine worker — the other end of ``serve/ipc.py``.

``worker_main`` is the spawn entrypoint one process-isolated replica
runs: build a private ``Engine`` (own jax client, pinned to this
replica's device), then loop — drain parent frames, step the engine,
ship completed results and heartbeat snapshots back. The worker holds
no authority: every request it runs also lives in the parent's shadow
bookkeeping, so this process can die AT ANY INSTRUCTION — SIGKILL,
SIGSEGV, OOM — and the supervisor replays its open work byte-identically
on a survivor.

The worker is TRANSPORT-AGNOSTIC (``serve/transport.py``): a spawned
child over a duplex pipe (``worker_main``), a spawned child that dials
back over TCP (``worker_main_dial``), and a worker started by hand on
another host (``python -m dalle_pytorch_tpu.serve.worker --connect
HOST:PORT --index N``, token in the ``DALLE_WORKER_TOKEN`` env var) all
run the SAME loop — a dialing worker authenticates with a HELLO and
receives its spec (params + config) over the socket, then is supervised
exactly like a local child. The invariants the worker owns:

  * **Results and the counters that count them ride the same frame.**
    A completion is shipped in a harvest frame whose snapshot already
    includes it; the parent absorbs results before the snapshot. The
    prefix of frames that survives a mid-write kill is therefore always
    a consistent state (see ipc.py's module docstring).
  * **A dead parent means exit, not a leak.** Every transport
    read/write and every idle nap goes through the connection; when the
    parent dies the transport EOFs/resets and the worker ``os._exit``\\ s
    — no orphaned interpreters pinning devices after a parent crash.
    Over a socket this covers the network deaths too: a reset or a
    stalled parent that stops reading surfaces as a transport error and
    the worker dies rather than running unsupervised.
  * **Every frame is sequenced.** The worker numbers its frames and
    verifies the parent's; a transport that loses, duplicates, or
    reorders delivery is caught as a typed protocol error on whichever
    side sees it first — never absorbed into the replay state.
  * **Local handles are stand-ins.** Admitted requests become child-
    local ``RequestHandle``\\ s (same request_id/queue_seq — replay
    identity survives the boundary); the engine fulfils them locally
    and the worker observes+ships the terminal result. The caller's
    real future never leaves the parent.
  * **The RSS watchdog dies loudly.** With ``rss_limit_mb`` set, the
    worker checks its real RSS (/proc/self/statm) every iteration and
    ``os._exit(137)``\\ s past the limit — the container OOM-kill
    convention, and exactly the abrupt no-goodbye death the supervisor
    must handle from a kernel OOM killer.
  * **Known compiles announce themselves.** A cold decode program or
    prefill bucket blocks this loop for seconds with no frames; the
    worker sends a compiling=True heartbeat BEFORE such a step
    (``Engine.compile_pending``), so the parent's hang deadline doesn't
    read warm-up as a wedge and hard-kill a healthy child.
"""

from __future__ import annotations

import os
import time
from typing import Dict

from dalle_pytorch_tpu.serve import ipc
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import transport as T

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# exit codes are protocol (the parent decodes them): 0 clean, 1 crash
# (after a best-effort CRASH frame), 3 parent/transport gone, 4 the
# parent rejected this worker's HELLO (bad token / index / version),
# 5 the spec's local checkpoint is missing/invalid (ipc.BAD_CKPT_EXIT),
# 137 RSS watchdog
PARENT_GONE_EXIT = 3
REJECTED_EXIT = 4


class WorkerCheckpointError(RuntimeError):
    """Typed local-checkpoint failure for a checkpoint-path attach spec
    (``ReplicaSet(worker_ckpt=...)``): the path the spec named is
    missing, fails ``checkpoint.validate`` (truncated payload, crc
    mismatch, absent manifest), or — in ``latest:`` form — no valid
    epoch exists at all. The worker ships the reason in a CRASH frame
    and dies with ``ipc.BAD_CKPT_EXIT`` (5), so the parent's /healthz
    shows an operator-actionable exit instead of a generic crash.
    ``record`` is the structured event."""

    def __init__(self, record: dict):
        super().__init__(
            f"worker checkpoint rejected: {record.get('reason')} "
            f"(path {record.get('path')!r})")
        self.record = record


def load_ckpt_params(spec: dict):
    """Resolve + validate + restore the params a checkpoint-path spec
    names. Two forms: a concrete checkpoint directory (gated by
    ``checkpoint.validate`` — never trust a checkpoint that a partial
    rsync may have torn), or ``latest:<models_dir>:<name>`` resolved
    through ``checkpoint.latest_valid`` (newest epoch that validates —
    the same trust rule auto-resume uses).

    The spec's serving TRANSFORMS then apply worker-side, in the same
    order the in-process CLI applies them — ``ckpt_use_ema`` swaps in
    the checkpoint's EMA weights (``cli.common.ema_as``, restored from
    the SAME resolved directory; a checkpoint without EMA is a typed
    rejection, exit 5), ``ckpt_quantize`` int8-quantizes the decode
    path (``models.dalle.quantize_for_decode``) — so a checkpoint-path
    attach serves weights byte-identical to ``--use_ema``/
    ``--quantize`` applied on the parent, without those weights ever
    crossing the wire."""
    from dalle_pytorch_tpu import checkpoint as ckpt
    from dalle_pytorch_tpu.utils.metrics import structured_event

    path = str(spec["ckpt_path"])
    if path.startswith("latest:"):
        try:
            _, models_dir, name = path.split(":", 2)
        except ValueError:
            raise WorkerCheckpointError(structured_event(
                "serve_worker_ckpt_invalid", path=path,
                reason="malformed latest:<models_dir>:<name> spec")) \
                from None
        found = ckpt.latest_valid(models_dir, name)
        if found is None:
            raise WorkerCheckpointError(structured_event(
                "serve_worker_ckpt_invalid", path=path,
                reason=f"no valid checkpoint for {name!r} under "
                       f"{models_dir!r}"))
        path = found[0]
    else:
        ok, reason = ckpt.validate(path)
        if not ok:
            raise WorkerCheckpointError(structured_event(
                "serve_worker_ckpt_invalid", path=path, reason=reason))
    params, _manifest = ckpt.restore_params(path)
    if spec.get("ckpt_use_ema"):
        ema = ckpt.restore_ema(path)
        if ema is None:
            raise WorkerCheckpointError(structured_event(
                "serve_worker_ckpt_invalid", path=path,
                reason="spec asks for EMA weights but the checkpoint "
                       "carries none (train with --ema_decay)"))
        from dalle_pytorch_tpu.cli.common import ema_as
        params = ema_as(ema, params)
    quantize = str(spec.get("ckpt_quantize") or "none")
    if quantize not in ("none", "int8", "int8_kv"):
        raise WorkerCheckpointError(structured_event(
            "serve_worker_ckpt_invalid", path=path,
            reason=f"unknown ckpt_quantize {quantize!r} (expected "
                   f"'none', 'int8', or 'int8_kv')"))
    if quantize != "none":
        from dalle_pytorch_tpu.models import dalle as D
        params = D.quantize_for_decode(params)
    return params


def rss_mb() -> int:
    """Resident set size in MiB — /proc on Linux; elsewhere, the
    ru_maxrss (PEAK, the best portable stand-in) with the platform's
    units: bytes on macOS, KiB on the rest."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE // (1 << 20)
    except (OSError, IndexError, ValueError):
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak >> 20 if sys.platform == "darwin" else peak >> 10


class _FrameSender:
    """The worker's one frame-writing point: every frame out carries
    the next tx sequence number, so delivery-order violations are
    detectable on the parent's side of any transport."""

    def __init__(self, transport, start_seq: int):
        self.transport = transport
        self.seq = int(start_seq)

    def send(self, kind: str, payload: dict) -> None:
        self.transport.send_bytes(ipc.encode_frame(kind, payload,
                                                   self.seq))
        self.seq += 1


def worker_main(spec: dict, conn) -> None:
    """Pipe-transport spawn entrypoint (``multiprocessing`` 'spawn'
    context — never fork a live jax runtime)."""
    _worker_shell(spec, T.PipeTransport(conn), start_seq=0)


def worker_main_dial(host: str, port: int, token: str,
                     index: int) -> None:
    """Socket-transport spawn entrypoint: dial the parent's listener,
    HELLO (token + protocol version + index), receive the spec over the
    authenticated socket, then run the same loop. Also the body of the
    hand-started remote worker (``main`` below)."""
    try:
        transport, spec = T.dial_parent(host, port, token, index)
    except T.IPCError as e:
        print(f"serve-worker[{index}]: attach rejected: {e}",
              flush=True)
        os._exit(REJECTED_EXIT)
    except OSError as e:
        print(f"serve-worker[{index}]: cannot reach parent "
              f"{host}:{port}: {e}", flush=True)
        os._exit(PARENT_GONE_EXIT)
    # seq 0 of each direction was spent on HELLO/HELLO_OK
    _worker_shell(spec, transport, start_seq=1)


def _worker_shell(spec: dict, transport, start_seq: int) -> None:
    """Run the loop; translate every way it can end into the exit-code
    protocol. Signals show up as negative exitcodes for the parent to
    decode."""
    sender = _FrameSender(transport, start_seq)
    try:
        _run(spec, transport, sender, rx_seq=start_seq)
    except (EOFError, BrokenPipeError, ConnectionResetError,
            ConnectionAbortedError):
        os._exit(PARENT_GONE_EXIT)  # parent/transport died: leak nothing
    except MemoryError:
        os._exit(ipc.OOM_EXIT)
    except WorkerCheckpointError as e:
        # typed, operator-actionable: ship the reason, die with the
        # checkpoint exit code (the parent decodes 5 as 'fix the path /
        # rsync the checkpoint', not as a crash to diff)
        try:
            sender.send(ipc.CRASH, {"error": repr(e)})
        except Exception:   # noqa: BLE001 — the transport may be gone
            pass
        os._exit(ipc.BAD_CKPT_EXIT)
    except BaseException as e:  # noqa: BLE001 — ship the reason, then die
        try:
            sender.send(ipc.CRASH, {"error": repr(e)})
        except Exception:   # noqa: BLE001 — the transport may be gone too
            pass
        os._exit(1)
    os._exit(0)


def _run(spec: dict, conn, sender: _FrameSender, rx_seq: int) -> None:
    from dalle_pytorch_tpu.resilience import faults

    # the parent decides which plan (if any) this child gets — NOT the
    # env var: fire-once for hard kills must outlive the child, so
    # faults.child_plan_for hands a plan to a replica's first spawn
    # only and a restarted child comes up clean
    if spec.get("faults"):
        faults.activate(faults.FaultPlan(**spec["faults"]))
    rss_limit = int(spec.get("rss_limit_mb") or 0)
    index = int(spec["index"])

    import jax

    from dalle_pytorch_tpu.serve.engine import Engine, MigrationError

    devices = jax.devices()
    params = spec["params"]
    if params is None:
        # checkpoint-path attach: the spec carried a path, not weights —
        # load + validate LOCALLY (a remote host's own checkpoint store,
        # never a multi-GB pickle over the wire)
        params = load_ckpt_params(spec)
    queue = S.RequestQueue(max_depth=1 << 30, clock=time.perf_counter)
    mesh_m = int(spec.get("devices_per_replica") or 1)
    if mesh_m > 1:
        # replica = mesh slice, in-child: same Engine surface, params +
        # KV sharded over this worker's local device slice
        from dalle_pytorch_tpu.parallel import serve_specs as SS
        from dalle_pytorch_tpu.serve.mesh_engine import MeshEngine
        device = SS.slice_devices(devices, int(spec["device_index"]),
                                  mesh_m)
        engine = MeshEngine(params, spec["cfg"], queue, complete=None,
                            clock=time.perf_counter, devices=device,
                            **spec["engine_kwargs"])
    else:
        device = (devices[int(spec["device_index"]) % len(devices)]
                  if spec.get("place") else None)
        if device is None:
            # Engine device_puts params itself when placed; unplaced, do
            # it here so the numpy pytree isn't re-uploaded every jit
            # call
            params = jax.device_put(params)
        engine = Engine(params, spec["cfg"], queue, complete=None,
                        clock=time.perf_counter, device=device,
                        **spec["engine_kwargs"])

    open_handles: Dict[int, S.RequestHandle] = {}
    # READY announces the weights generation this worker actually
    # serves: during a rolling upgrade the parent re-spawns workers on
    # a NEW ckpt path/params, and the announcement lets the supervisor
    # (and /healthz) verify the attach landed on the generation it
    # asked for — a stale worker dialing a reshaped fleet advertises
    # itself instead of silently serving old weights
    sender.send(ipc.READY, {"pid": os.getpid(), "device": str(device),
                            "rss_mb": rss_mb(),
                            "weights_version": engine.weights_version})

    hb_interval = float(spec.get("heartbeat_interval_s", 0.05))
    idle_sleep = float(spec.get("idle_sleep_s", 0.002))
    last_hb = 0.0
    flight_seq = 0      # ring increments already shipped to the parent

    def send_snapshot(kind: str, results=None,
                      compiling: bool = False) -> None:
        nonlocal last_hb, flight_seq
        chunks = engine.decode_steps // engine.chunk_steps
        snap = ipc.engine_snapshot(engine, chunks, rss_mb(), compiling)
        payload = {"snap": snap}
        # the flight ring's INCREMENTS ride every snapshot frame: the
        # parent's mirror is therefore as fresh as the last frame that
        # landed, which is exactly what a SIGKILL post-mortem can
        # honestly have (spans stamped after the last frame die with
        # this process — a consistent prefix, never a lie)
        flight_seq, events = engine.flight.since(flight_seq)
        if events:
            payload["events"] = events
        if results is not None:
            payload["results"] = results
        sender.send(kind, payload)
        last_hb = time.perf_counter()

    while True:
        # 1. parent frames (admission + control). recv raising EOF /
        # reset here IS the parent-death path _worker_shell handles;
        # a broken sequence from the parent is a protocol error the
        # worker dies loudly on (CRASH frame + exit 1).
        while conn.poll(0):
            kind, payload, seq = ipc.decode_frame(conn.recv_bytes())
            rx_seq = ipc.seq_check(seq, rx_seq)
            if kind == ipc.ADMIT:
                now = time.perf_counter()
                for d in payload["requests"]:
                    h = S.RequestHandle.from_wire(d, now)
                    open_handles[h.request.request_id] = h
                    # requeue, not submit: the handle keeps the parent-
                    # assigned request_id and arrival seq — replay
                    # identity and ordering survive the boundary
                    queue.requeue(h, count=False)
            elif kind == ipc.FENCE:
                engine.fence()
                sender.send(ipc.BYE, {"reason": "fenced"})
                return
            elif kind == ipc.SHUTDOWN:
                engine.cancel_active("server shutdown")
                for h in queue.drain():
                    h.fulfill(S.Result(
                        status=S.CANCELLED,
                        request_id=h.request.request_id,
                        reason="server shutdown"))
                sender.send(ipc.BYE, {"reason": "shutdown"})
                return
            elif kind == ipc.STATS_REQ:
                sender.send(ipc.STATS, {"stats": engine.stats()})
            elif kind == ipc.MIGRATE_OUT:
                # export the named request's live slot and ship the
                # snapshot back. Success VACATES the slot: the request
                # leaves this worker un-fulfilled (the parent moves its
                # shadow to the target), so it is dropped from
                # open_handles WITHOUT a result frame — the target's
                # completion ships it.
                rid = int(payload["request_id"])
                try:
                    snap, _h = engine.export_request(rid)
                except MigrationError as e:
                    sender.send(ipc.MIGRATE_OUT, {
                        "request_id": rid, "ok": False,
                        "reason": e.reason, "error": str(e)})
                except Exception as e:    # noqa: BLE001 — typed fallback
                    sender.send(ipc.MIGRATE_OUT, {
                        "request_id": rid, "ok": False,
                        "reason": "transfer", "error": repr(e)})
                else:
                    open_handles.pop(rid, None)
                    sender.send(ipc.MIGRATE_OUT, {
                        "request_id": rid, "ok": True, "snap": snap})
            elif kind == ipc.MIGRATE_IN:
                # install an exported slot here; the stand-in handle
                # import_slot builds from the payload's wire form joins
                # open_handles so its completion ships as a normal
                # harvest result. A failed import leaves this engine
                # untouched (import_slot discards partial state) — the
                # NACK tells the parent to fall back to replay.
                snap = payload["snap"]
                rid = int(snap.get("request_id", -1))
                try:
                    slot_i = engine.import_slot(snap)
                except MigrationError as e:
                    sender.send(ipc.MIGRATE_ACK, {
                        "request_id": rid, "ok": False,
                        "reason": e.reason, "error": str(e)})
                except Exception as e:    # noqa: BLE001 — typed fallback
                    sender.send(ipc.MIGRATE_ACK, {
                        "request_id": rid, "ok": False,
                        "reason": "transfer", "error": repr(e)})
                else:
                    open_handles[rid] = engine.slots[slot_i].handle
                    sender.send(ipc.MIGRATE_ACK,
                                {"request_id": rid, "ok": True})
            else:
                raise ipc.IPCError(
                    f"unexpected frame kind {kind!r} from parent")

        chunks = engine.decode_steps // engine.chunk_steps
        # the soft catalog (crash raises -> CRASH frame + exit 1; hang
        # sleeps -> missed heartbeats -> the parent hard-kills), the
        # hard catalog (real self-SIGKILL/SIGSEGV, OOM against the
        # watchdog, a corrupt frame), and the NETWORK catalog (reset
        # mid-frame, torn frame, stalled socket, duplicate/reordered
        # frames) all run here, making every serve fault
        # process-drivable
        faults.on_replica_chunk(index, chunks)
        faults.on_worker_chunk(index, chunks,
                               emit_frame=conn.send_bytes,
                               rss_limit_mb=rss_limit, rss_mb=rss_mb,
                               transport=conn, sender=sender)

        # 2. RSS watchdog: die the way a container memory kill does —
        # abruptly, with no goodbye frame, exit 137
        if rss_limit and rss_mb() > rss_limit:
            os._exit(ipc.OOM_EXIT)

        # 3. announce a known-blocking compile BEFORE entering it
        if engine.compile_pending():
            send_snapshot(ipc.HEARTBEAT, compiling=True)

        busy = engine.step_once()

        # 4. ship completions. Batched under the pipe's atomic-write
        # size; ONLY the final batch carries the snapshot, because the
        # snapshot counts every completion in the sweep — a counter
        # must never arrive ahead of the result it counted.
        done = [rid for rid, h in open_handles.items() if h.done()]
        if done:
            wires = []
            for rid in done:
                h = open_handles.pop(rid)
                w = h.result(timeout=0).to_wire()
                if h.trace is not None:
                    # the stand-in trace's spans go home with the
                    # result — the parent merges them into the
                    # caller's timeline (scheduler.RequestHandle
                    # .from_wire seeded the same trace_id)
                    w["spans"] = h.trace.wire_spans()
                wires.append(w)
            for i in range(0, len(wires), ipc.HARVEST_BATCH):
                batch = wires[i:i + ipc.HARVEST_BATCH]
                if i + ipc.HARVEST_BATCH >= len(wires):
                    send_snapshot(ipc.HARVEST, results=batch)
                else:
                    sender.send(ipc.HARVEST,
                                {"results": batch, "snap": None})
        elif time.perf_counter() - last_hb >= hb_interval:
            send_snapshot(ipc.HEARTBEAT)

        # 5. idle nap ON THE TRANSPORT: wakes early for new admissions
        # and notices a dead parent even with nothing to do
        if not busy and engine.idle():
            conn.poll(idle_sleep)


def main(argv=None) -> None:
    """The hand-started / launcher-started worker (remote attach):

        DALLE_WORKER_TOKEN=<token> python -m dalle_pytorch_tpu.serve.worker \\
            --connect HOST:PORT --index N

    Dials the serving parent's ``--transport socket`` listener,
    authenticates, receives its spec over the socket, and serves as
    replica N until the parent fences it, shuts it down, or dies (any
    of which ends this process — a worker never outlives its parent's
    interest in it)."""
    import argparse

    p = argparse.ArgumentParser(
        description="dial into a serve_dalle --transport socket parent "
                    "as one engine-replica worker")
    p.add_argument("--connect", required=True, metavar="HOST:PORT",
                   help="the parent's worker endpoint "
                        "(serve_dalle --worker_endpoint)")
    p.add_argument("--index", type=int, required=True,
                   help="the replica index this worker serves as")
    p.add_argument("--token", default="",
                   help=f"HELLO token (prefer the {T.TOKEN_ENV} env "
                        f"var — argv is visible in `ps`)")
    args = p.parse_args(argv)
    token = args.token or os.environ.get(T.TOKEN_ENV, "")
    if not token:
        raise SystemExit(f"no attach token: set {T.TOKEN_ENV} or pass "
                         f"--token")
    host, port = T.parse_endpoint(args.connect)
    worker_main_dial(host, port, token, args.index)


if __name__ == "__main__":
    main()
