"""Paged KV-cache subsystem: the block-pool memory manager.

The dense slot pool (serve/engine.py, ``kv="dense"``) reserves
``num_slots × seq_len`` KV rows of HBM per layer up front, so HBM — not
compute — caps serving concurrency: a slot 10 tokens into a 1280-token
sequence holds 1280 rows of memory. Paged KV (PAPERS.md "Ragged Paged
Attention"; the Gemma-on-TPU serving study credits this exact mechanism
for most of its throughput headroom) breaks the cache into fixed-size
PAGES shared by every slot:

  * the device side is a page pool ``(depth, num_pages, heads,
    page_size, dim_head)`` per K and V (``init_page_pool``; int8 variant
    carries per-row scale pages) plus per-slot block tables
    ``(num_slots, max_pages)`` int32 mapping logical page j → physical
    page id — ``ops.decode.paged_view`` / ``_store_rows_paged`` are the
    gather/scatter through them, and ``ops.paged_attention`` is the
    Pallas kernel that consumes the tables in place
    (``paged_attn='kernel'``, which also imposes the page-size tile
    constraint ``validate_page_size`` gates);
  * the host side is THIS module's ``PageAllocator``: a free-list over
    physical pages. Physical page 0 is reserved as the TRASH page —
    dead slots park their writes there (see ops/decode.py), so it is
    never handed out;
  * the lifecycle is allocate-on-admission for the prompt span, grow by
    one page as ``pos`` crosses a page boundary (the engine maps ahead
    of every fused K-step chunk, so growth never needs a mid-chunk
    host sync), and free-on-completion/expiry/eviction.

Speculative decode (``speculative=k``) changes only the map-ahead
HORIZON, never the lifecycle: the engine provisions ``chunk_steps × k``
rows per chunk (the most a chunk can deliver at full acceptance)
instead of ``chunk_steps``. There is NO allocation churn on rejection —
a rejected draft's K/V rows sit above the slot's committed ``pos`` on
already-mapped pages and are simply overwritten by the next round's
k-wide write before ``pos`` ever crosses them, so pages are never
unmapped, shrunk, or re-requested mid-request; ``pos`` (and therefore
the page high-water mark) only moves forward. A low-acceptance slot
just reaches its map-ahead pages later than the estimate assumed; the
engine tightens the position estimate at every harvest so the horizon
tracks delivered tokens, not drafted ones.

Overcommit is the point: the engine may run more slots than
``num_pages`` could hold at full length, because concurrent requests sit
at ragged positions. When the pool genuinely runs out mid-decode, the
typed ``PagePoolExhausted`` backpressure path EVICTS the lowest-priority
active request back to the queue — pages freed, request re-queued with
its original handle, never dropped — and deterministic sampling replays
its exact tokens on re-admission (docs/SERVING.md "Paged KV").

Module-level imports stay jax-free (the ``serve`` package's lazy-import
discipline): queue-side callers can type-check against
``PagePoolExhausted`` before a backend exists.
"""

from __future__ import annotations

from typing import Dict, List

from dalle_pytorch_tpu.utils.metrics import structured_event

# physical page 0 is reserved: dead slots' parked writes land here, and
# unmapped block-table entries point here (reads of it are never attended)
TRASH_PAGE = 0

# the ragged paged-attention kernel's tile constraints
# (ops/paged_attention.py): a page is the kernel's K-tile, staged whole
# into VMEM, so its row count must be at least one f32 sublane tile (8)
# and a lane-friendly multiple of 8 — Mosaic cannot tile a 4-row page.
# The gather path has no such floor (any page_size works there).
KERNEL_MIN_PAGE_SIZE = 8
KERNEL_PAGE_MULTIPLE = 8


class PageSizeError(ValueError):
    """Typed page-size rejection at pool init: the configured
    ``page_size`` cannot feed the ragged paged-attention kernel
    (``ops/paged_attention.py`` stages one page per DMA as a VMEM
    K-tile, so pages must be >= the 8-row f32 sublane tile and a
    multiple of 8 lanes' worth of rows). Raised HERE, with the
    constraint named, instead of failing opaquely inside
    ``pl.pallas_call``. ``record`` is the structured event."""

    def __init__(self, record: dict):
        super().__init__(
            f"page_size={record.get('page_size')} cannot feed the "
            f"ragged paged-attention kernel (ops/paged_attention.py): "
            f"pages are staged whole into VMEM as the kernel's K-tile, "
            f"so page_size must be >= {record.get('min_page_size')} "
            f"(the f32 sublane tile) and a multiple of "
            f"{record.get('page_multiple')}. Use --paged_attn gather "
            f"for arbitrary page sizes.")
        self.record = record


def validate_page_size(page_size: int) -> None:
    """Gate a pool's ``page_size`` against the kernel tile constraints
    — called at pool init when ``paged_attn='kernel'`` is selected (and
    again by the kernel entry itself, so a direct caller cannot reach
    the opaque Mosaic failure either)."""
    ps = int(page_size)
    if ps < KERNEL_MIN_PAGE_SIZE or ps % KERNEL_PAGE_MULTIPLE:
        raise PageSizeError(structured_event(
            "serve_page_size_invalid", page_size=ps,
            min_page_size=KERNEL_MIN_PAGE_SIZE,
            page_multiple=KERNEL_PAGE_MULTIPLE))


class PageReleaseUnderflow(ValueError):
    """Typed refcount underflow: a release of a page whose refcount is
    already zero (it is already on the free list). Under copy-on-write
    sharing this is the same bug class the old double-release guard
    caught — a page freed past its reference count would sit in the
    free list while a sibling's block table still maps it, and the next
    allocation would hand it to a SECOND live slot whose decode writes
    would silently interleave with the sibling's reads. Fail at the
    bug's site. ``record`` is the structured event."""

    def __init__(self, record: dict):
        super().__init__(
            f"double release of page {record.get('page')}: its refcount "
            f"is already 0 (it is already free) — freeing it again "
            f"would let two live slots end up sharing it")
        self.record = record


class PagePoolExhausted(RuntimeError):
    """Typed page backpressure: an allocation the free-list cannot serve.
    ``record`` is the structured event (kind ``serve_page_exhausted``)
    carrying the shortfall — the engine's eviction path catches this and
    converts it into a requeue, never a dropped request or a wedged
    loop."""

    def __init__(self, record: dict):
        super().__init__(
            f"page pool exhausted: need {record.get('pages_needed')}, "
            f"free {record.get('pages_free')} of "
            f"{record.get('pages_capacity')}")
        self.record = record


def pages_for(rows: int, page_size: int) -> int:
    """Pages needed to hold ``rows`` KV rows (ceil division)."""
    return -(-rows // page_size)


def init_page_pool(cfg, num_pages: int, page_size: int, dtype=None,
                   quantized: bool = False) -> dict:
    """Device-resident page pool: ``(depth, num_pages, heads, page_size,
    dim_head)`` K/V buffers (int8 + per-row f32 scale pages when
    ``quantized`` — the same layout/accuracy contract as
    ``ops.decode.init_cache``, so int8-KV composes with paging
    unchanged)."""
    import jax.numpy as jnp
    if dtype is None:
        dtype = jnp.float32
    shape = (cfg.depth, num_pages, cfg.heads, page_size, cfg.dim_head)
    if quantized:
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:-1], jnp.float32),
                "v_scale": jnp.zeros(shape[:-1], jnp.float32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def visible_table_view(block_tables, visible):
    """Visibility-trimmed view of per-slot block tables: row i of the
    result lists the PHYSICAL pages behind slot i's visible logical
    pages — ``visible`` (b, W) int32 is the per-position visible-page
    list ``ops.sparse.visible_pages`` precomputes, indexed at each
    slot's current position (sparsity-aware decode reads,
    docs/SERVING.md "Sparse decode reads"). Entries past the visible
    count mirror whatever the padding entries map (logical page 0);
    consumers must mask those columns — the view narrows the READ, the
    mask still decides attendance. Traced code (jax.numpy), called
    from inside the fused decode program."""
    import jax.numpy as jnp
    return jnp.take_along_axis(block_tables, visible, axis=1)


def snapshot_page(pool: dict, page) -> dict:
    """Device-side copy of ONE physical page across every layer (and the
    int8 pool's scale pages): ``{k: (depth, heads, page_size[, dh])}``.
    The prefix cache's copy-on-write source — taken at insert time,
    BEFORE the inserting request's decode can write past its prompt
    span into the same physical page. Traced (jax.numpy); the engine
    jits it once per pool layout."""
    return {k: pool[k][:, page] for k in pool}


def restore_page(pool: dict, page, snap: dict) -> dict:
    """Write a ``snapshot_page`` copy into physical page ``page`` — the
    copy-on-write FORK: a warm-hit slot gets a private page whose
    prompt-tail rows are byte-identical to the cached boundary page, so
    its decode appends diverge without ever touching the shared copy.
    Traced; the engine jits it once per pool layout (with the pool's
    shardings pinned on a mesh engine, so the fork can never drift the
    KV store's placement between fused chunks)."""
    return {k: pool[k].at[:, page].set(snap[k]) for k in pool}


def pool_bytes(pool: dict) -> int:
    """Resident HBM bytes of a pool (or of a dense cache dict) — the
    number ``bench_serve --serve_kv`` compares across layouts."""
    return int(sum(x.nbytes for x in pool.values()))


def modeled_kv_bytes(cfg, *, kv: str, num_slots: int, total_len: int,
                     page_size: int = 0, num_pages: int = 0,
                     quantized: bool = False,
                     dtype_bytes: int = 4) -> int:
    """KV-store bytes from CONFIG alone — the same number
    ``pool_bytes`` measures on a live engine's arrays, computable
    without building one (the replica set's /stats for child-process
    engines, whose pools live in another interpreter, and bench's
    HBM-budget math read this). Mirrors the engine's defaults:
    ``page_size`` 0 -> min(16, total_len); ``num_pages`` 0 -> fully
    provisioned (num_slots full sequences + the trash page)."""
    depth, heads, dh = cfg.depth, cfg.heads, cfg.dim_head
    if kv == "paged":
        ps = int(page_size) or min(16, total_len)
        pages = int(num_pages) or \
            num_slots * pages_for(total_len, ps) + 1
        rows = pages * ps
    else:
        rows = num_slots * total_len
    per_row = (1 + 4 / dh) if quantized else dtype_bytes
    # k + v; quantized stores int8 rows (1 byte/elem) plus one f32
    # scale per row — expressed per element as 1 + 4/dh
    return int(2 * depth * heads * rows * dh * per_row)


class PageAllocator:
    """Host-side free-list over physical pages ``[1, num_pages)`` (page 0
    is the reserved trash page), REFCOUNTED for copy-on-write sharing
    (docs/SERVING.md 'Prefix cache & per-request CFG'): ``alloc`` hands
    out pages at refcount 1, ``retain`` maps an already-live page into
    another owner's block table (physical sharing — the prefix cache's
    warm hit), and ``release`` decrements, returning a page to the free
    list only when its LAST reference drops. ``in_use`` counts physical
    pages — a page shared by five block tables is one page of HBM —
    which is what keeps /stats' ``pages_in_use`` and the modeled-vs-live
    pool-bytes comparisons exact under sharing. Single-threaded by
    design — the engine owns it under its step lock, like every other
    piece of slot bookkeeping."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (one trash page + at least one "
                f"allocatable), got {num_pages}")
        self.num_pages = int(num_pages)
        # pop() hands out the lowest free id first — deterministic page
        # placement makes failures reproducible
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._free_set = set(self._free)   # O(1) double-release check
        self._refs: Dict[int, int] = {}    # live page -> reference count
        self.peak_in_use = 0
        self.allocs = 0
        self.retains = 0

    @property
    def capacity(self) -> int:
        return self.num_pages - 1          # trash page excluded

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        # PHYSICAL pages: a shared page counts once (refcounts never
        # inflate residency — that is the whole point of sharing)
        return self.capacity - self.free

    @property
    def pages_shared(self) -> int:
        """Physical pages mapped by more than one owner (refcount >= 2)
        — the /stats sharing gauge."""
        return sum(1 for r in self._refs.values() if r >= 2)

    @property
    def refs_saved(self) -> int:
        """Pages of HBM sharing is currently saving: the sum over live
        pages of (refcount - 1) — what a refcount-blind pool would have
        allocated extra."""
        return sum(r - 1 for r in self._refs.values())

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def alloc(self, n: int) -> List[int]:
        """Hand out ``n`` physical page ids at refcount 1, or raise the
        typed ``PagePoolExhausted`` (the caller decides between
        deferring the request and evicting a victim)."""
        if n > self.free:
            raise PagePoolExhausted(structured_event(
                "serve_page_exhausted", pages_needed=int(n),
                pages_free=self.free, pages_in_use=self.in_use,
                pages_capacity=self.capacity))
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        for p in out:
            self._refs[p] = 1
        self.allocs += n
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return out

    def retain(self, pages: List[int]) -> None:
        """Add one reference to each (already-live) page — the prefix
        cache's warm hit mapping existing prompt pages into a new
        slot's block table, and the index's own hold on an inserted
        prefix. Retaining a free page is a hard error: its content is
        gone the moment the next ``alloc`` hands it out."""
        for p in pages:
            p = int(p)
            if not 1 <= p < self.num_pages:
                raise ValueError(f"page id {p} was never allocatable")
            if p in self._free_set or p not in self._refs:
                raise ValueError(
                    f"retain of free page {p}: only a live (allocated) "
                    f"page can gain a reference — a free page's content "
                    f"is forfeit to the next alloc")
            self._refs[p] += 1
            self.retains += 1

    def release(self, pages: List[int]) -> None:
        """Drop one reference per page (completion/expiry/eviction/
        prefix-cache eviction); a page returns to the free list only at
        refcount zero — an eviction victim whose pages are still mapped
        by a sibling's block table (or held by the prefix index) must
        NOT hand them to the next allocation. Releasing past zero is
        the typed ``PageReleaseUnderflow``: the refcounted form of the
        double-release guard, failing at the bug's site instead of
        letting two live slots interleave writes in one page."""
        for p in pages:
            p = int(p)
            if not 1 <= p < self.num_pages:
                raise ValueError(f"page id {p} was never allocatable")
            if p in self._free_set or self._refs.get(p, 0) <= 0:
                raise PageReleaseUnderflow(structured_event(
                    "serve_page_release_underflow", page=p,
                    pages_free=self.free, pages_in_use=self.in_use))
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)
                self._free_set.add(p)
