"""Serving front-end: the threaded Python API and the stdlib HTTP server.

``InferenceServer`` wires the three pipeline stages together —
``scheduler.RequestQueue`` (admission) -> ``engine.Engine`` (slot-batched
decode, its own thread) -> ``postprocess.PostProcessor`` (VAE/CLIP, its
own thread) — and owns their lifecycle. Backend bring-up goes through the
SAME deadline/backoff/jitter discipline as every other entry point
(``resilience.retry``): a wedged TPU claim surfaces as a structured
``BringupError`` instead of a hung server.

Two call surfaces:
  * Python: ``submit(codes, ...) -> RequestHandle`` / ``stats()`` — what
    tests, the bench, and embedders use;
  * HTTP (``serve_http``): POST /generate {"codes": [...] | "caption":
    "...", sampling knobs...} blocks for the result (429 on queue-full,
    504 on deadline, both with the structured record as the JSON body);
    GET /stats and /healthz for operators. The stdlib ThreadingHTTPServer
    is deliberate — one dependency-free front-end; a production mesh
    would sit a real gateway in front of the same Python API.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from dalle_pytorch_tpu.serve import engine as engine_mod
from dalle_pytorch_tpu.serve import postprocess as post_mod
from dalle_pytorch_tpu.serve import scheduler as S


class InferenceServer:
    """Continuous-batching text->image service. ``replicas=1`` (the
    default) runs one engine on one thread; ``replicas=N`` fronts N
    supervised engine replicas with the same shared queue through
    ``serve.replica.ReplicaSet`` — replica crash/hang/drain fails over
    with zero lost requests via deterministic replay, and capacity loss
    degrades to typed ``QueueFull`` backpressure (docs/SERVING.md
    'Replica set & failover'). ``isolation='process'`` additionally
    runs each replica's engine in a supervised child process, so a
    SIGSEGV/SIGKILL/OOM kill of one replica cannot take the server
    down (docs/SERVING.md 'Process isolation'); /healthz then reports
    per-replica PID, restart count, last exit signal, and child RSS,
    503 still only when ALL replicas are dead."""

    def __init__(self, params: dict, vae_params: dict, cfg, *,
                 num_slots: int = 4, queue_depth: int = 64,
                 chunk_steps: int = 8,
                 prefill_buckets=None,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 paged_attn: str = "gather",
                 sparse_reads: bool = False,
                 speculative: int = 0,
                 draft_layers: int = 0,
                 prefix_cache: bool = False,
                 preview_every: int = 0,
                 stream_max_events: int = 256,
                 default_cfg_scale: float = 0.0,
                 replicas: int = 1,
                 replica_roles=None,
                 mesh_devices: int = 1,
                 weights_version: str = "0",
                 max_replicas: int = 0,
                 autoscale=None,
                 admin_token: Optional[str] = None,
                 load_weights: Optional[Callable] = None,
                 heartbeat_s: float = 5.0,
                 isolation: str = "thread",
                 child_rss_limit_mb: int = 0,
                 transport: str = "pipe",
                 worker_endpoint: str = "127.0.0.1:0",
                 worker_cmd: Optional[str] = None,
                 attach_token: Optional[str] = None,
                 worker_ckpt: Optional[str] = None,
                 worker_use_ema: bool = False,
                 worker_quantize: str = "none",
                 clip_params: Optional[dict] = None, clip_cfg=None,
                 decode_images: bool = True,
                 metrics=None, log_every: int = 50,
                 profile_dir: Optional[str] = None,
                 encode: Optional[Callable[[str], List[int]]] = None,
                 init_deadline_s: float = 0.0, init_retries: int = 3):
        self.cfg = cfg
        self.metrics = metrics
        self.encode = encode
        # default sink for POST /admin/profile (a request body may name
        # its own dir; with neither, the capture is a typed refusal)
        self.profile_dir = profile_dir or None
        # server-wide guidance default: a request that doesn't carry
        # its own cfg_scale samples with this one (0 = unguided)
        self.default_cfg_scale = float(default_cfg_scale)
        if self.default_cfg_scale < 0:
            raise ValueError(f"default_cfg_scale must be >= 0, got "
                             f"{default_cfg_scale}")
        self.init_deadline_s = init_deadline_s
        self.init_retries = init_retries
        self.replicas = int(replicas)
        # the elastic operator surface (docs/SERVING.md 'Elastic
        # fleet'): POST /admin/scale authenticates against this token
        # (generated when the caller supplies none — printed by the
        # CLI, never guessable), add/remove/drain/upgrade delegate to
        # the replica set, and an AutoscalePolicy drives the same
        # calls off the load signals. A single-replica server with
        # autoscale or a max_replicas headroom cap still fronts a
        # ReplicaSet — elasticity needs supervised slots to grow into.
        import secrets as _secrets
        self.admin_token = admin_token or _secrets.token_hex(16)
        self.autoscale_policy = autoscale
        self.autoscaler = None
        self.load_weights = load_weights
        self.weights_version = str(weights_version)
        self.max_replicas = int(max_replicas)
        self._is_set = (self.replicas > 1 or autoscale is not None
                        or self.max_replicas > 1)
        self.replica_roles = tuple(replica_roles) if replica_roles \
            else None
        if self.replica_roles and not self._is_set:
            # a lone engine has nobody to migrate warm requests to —
            # the disaggregated shape needs a set
            raise ValueError("replica_roles requires a replica set "
                             "(replicas >= 2)")
        if autoscale is not None:
            # the policy caps and the set cap must agree, or the
            # autoscaler would ask for replicas the set typed-rejects
            self.max_replicas = max(self.max_replicas,
                                    autoscale.max_replicas)
        self.mesh_devices = int(mesh_devices)
        if self.mesh_devices < 1:
            raise ValueError(f"mesh_devices must be >= 1, got "
                             f"{mesh_devices}")
        if worker_ckpt is not None and transport != "socket":
            # same silent-misconfiguration hazard as worker_cmd: the
            # operator believes workers load locally when they don't.
            # (socket itself already implies process isolation and
            # replicas >= 2 via the checks below)
            raise ValueError(
                "worker_ckpt requires transport='socket' — its point "
                "is that a worker loads the checkpoint from its OWN "
                "host's store instead of receiving params over a pipe")
        if isolation == "process" and self.replicas < 2:
            # process isolation exists to keep the SET alive through a
            # child death; a 1-replica process set is legal for the
            # ReplicaSet API (restart-with-replay), but the server's
            # contract is replicas>1 — fail loudly instead of serving a
            # shape the operator almost certainly didn't mean
            raise ValueError("isolation='process' requires replicas >= 2")
        if transport != "pipe" and isolation != "process":
            # a transport only exists between a parent and worker
            # processes; silently ignoring the flag would let an
            # operator believe they were host-isolated when they weren't
            raise ValueError(
                f"transport={transport!r} requires isolation='process'")
        if worker_cmd is not None and self.replicas < 2:
            # the single-engine path would drop the launcher command on
            # the floor — same silent-misconfiguration hazard as above
            raise ValueError("worker_cmd requires replicas >= 2 with "
                             "isolation='process' and "
                             "transport='socket'")
        self.isolation = str(isolation)

        self.queue = S.RequestQueue(
            max_depth=queue_depth,
            # a prompt the slot pool can't hold is rejected HERE (typed
            # InvalidRequest / HTTP 400), before it can reach the engine
            max_prompt_len=cfg.text_seq_len,
            # submit-time rejects land in the flight ring (always on)
            # AND the JSONL sink (when configured) — self.engine exists
            # by the first runtime submit
            on_event=self._queue_event)
        if self._is_set:
            from dalle_pytorch_tpu.serve import replica as replica_mod
            self.engine = replica_mod.ReplicaSet(
                params, cfg, self.queue, replicas=self.replicas,
                num_slots=num_slots, chunk_steps=chunk_steps,
                prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                speculative=speculative, draft_layers=draft_layers,
                prefix_cache=prefix_cache, preview_every=preview_every,
                heartbeat_s=heartbeat_s, isolation=isolation,
                child_rss_limit_mb=child_rss_limit_mb,
                transport=transport, worker_endpoint=worker_endpoint,
                worker_cmd=worker_cmd, attach_token=attach_token,
                worker_ckpt=worker_ckpt,
                worker_use_ema=worker_use_ema,
                worker_quantize=worker_quantize,
                devices_per_replica=self.mesh_devices,
                weights_version=self.weights_version,
                max_replicas=self.max_replicas,
                roles=self.replica_roles)
            if self.autoscale_policy is not None:
                from dalle_pytorch_tpu.serve.autoscale import Autoscaler
                # the set's RecordingMetrics: every autoscale_decision
                # lands in the set-level flight ring (and the JSONL
                # sink when one exists) — "why did the fleet reshape"
                # is answerable from /debug/events alone
                self.autoscaler = Autoscaler(
                    self.engine, self.autoscale_policy,
                    metrics=self.engine.metrics)
        elif self.mesh_devices > 1:
            # ONE logical engine pjit-sharded over a device mesh — the
            # serve surface is identical (docs/SERVING.md 'Mesh-sharded
            # engine'), so the single-engine thread loop below drives it
            # unchanged
            import jax

            from dalle_pytorch_tpu.serve.mesh_engine import MeshEngine
            from dalle_pytorch_tpu.parallel import serve_specs as SS
            self.engine = MeshEngine(
                params, cfg, self.queue,
                devices=SS.slice_devices(jax.devices(), 0,
                                         self.mesh_devices),
                num_slots=num_slots,
                chunk_steps=chunk_steps, prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                speculative=speculative, draft_layers=draft_layers,
                prefix_cache=prefix_cache, preview_every=preview_every,
                weights_version=self.weights_version,
                model_version=self.weights_version)
        else:
            self.engine = engine_mod.Engine(
                params, cfg, self.queue, num_slots=num_slots,
                chunk_steps=chunk_steps, prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                speculative=speculative, draft_layers=draft_layers,
                prefix_cache=prefix_cache, preview_every=preview_every,
                weights_version=self.weights_version,
                model_version=self.weights_version)

        # the postprocess stage is built AFTER the engine(s) so its
        # structured events tee into the same flight ring the engine's
        # do (RecordingMetrics — docs/OBSERVABILITY.md)
        self.post = None
        if decode_images:
            self.post = post_mod.PostProcessor(
                params, vae_params, cfg, clip_params=clip_params,
                clip_cfg=clip_cfg, metrics=self.engine.metrics,
                on_fulfill=self._record_latency)

        # -- streaming & fan-out plumbing (docs/SERVING.md 'Streaming,
        # fan-out & variable resolution') --------------------------------
        # progressive previews need somewhere to decode pixels: the
        # engine's harvest-side hook hands the image-token prefix to the
        # postprocess worker (best-effort, never blocking the engine)
        self.stream_max_events = int(stream_max_events)
        self.preview_every = int(preview_every)
        if self.post is not None and preview_every:
            self.engine.on_preview = self.post.submit_preview
        # live registries swept lazily at stats() time: sinks whose
        # channel hasn't ended (streams_active) and group futures not
        # yet assembled (groups_in_flight). Completed paged+prefix
        # groups credit fanout_pages_saved — the COW dividend: pages
        # the siblings' prompt spans would have cost as N cold prefills
        self._streams: list = []
        self._groups: list = []
        self._stream_lock = threading.Lock()
        self.fanout_pages_saved = 0
        self.groups_completed = 0
        self._page_size = int(page_size) or (min(16, cfg.seq_len)
                                             if kv == "paged" else 0)
        self._cow_sharing = (kv == "paged" and prefix_cache)

        # /metrics exposition (obs/registry.py): the sliding-window
        # latency histograms, labeled per weights_version so a rolling
        # upgrade's two generations are distinguishable on a dashboard.
        # Counters/gauges are projected from the live /stats dicts at
        # scrape time — one source of truth, no second set of state.
        from dalle_pytorch_tpu.obs import registry as obs_registry
        self.registry = obs_registry.Registry()
        self.hist_e2e = self.registry.histogram(
            "dalle_serve_e2e_latency_seconds",
            "End-to-end latency of successful requests "
            "(submit -> caller-visible fulfilment)")
        self.hist_queue_wait = self.registry.histogram(
            "dalle_serve_queue_wait_seconds",
            "Queue wait of successful requests (submit -> admission)")
        self.hist_prefill = self.registry.histogram(
            "dalle_serve_prefill_seconds",
            "Prefill/admission span per successful request "
            "(pop -> slotted; trace span prefill_admit)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0))
        self.hist_ms_per_token = self.registry.histogram(
            "dalle_serve_decode_ms_per_token",
            "Decode milliseconds per generated token, per successful "
            "request",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                     50.0, 100.0, 250.0, 1000.0))
        self.hist_migration = self.registry.histogram(
            "dalle_serve_migration_seconds",
            "Wall seconds per successful live slot migration "
            "(export -> installed on the target)",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
        # serializes /admin/profile's sibling-capture check + arm (two
        # concurrent POSTs targeting different thread-mode replicas
        # must not both pass the per-process-singleton guard)
        self._profile_arm_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- stage glue ---------------------------------------------------------

    def _queue_event(self, rec: dict) -> None:
        fl = getattr(self.engine, "flight", None)
        if fl is not None:
            fl.record(rec)
        if self.metrics is not None:
            self.metrics.event(**rec)

    def _record_latency(self, result: S.Result) -> None:
        # successful completions only: mixing in error results (whose
        # wait ends early) would deflate the percentiles exactly when a
        # failing dependency makes the tail matter most
        if not result.ok:
            return
        # histogram feed: exactly once per DELIVERED request (this hook
        # runs at the single fulfilment funnel), so the e2e histogram's
        # count equals distinct delivered requests — the /metrics
        # acceptance contract. weights_version labels keep a rolling
        # upgrade's generations separable.
        v = result.weights_version or ""
        self.hist_e2e.observe(result.total_s, weights_version=v)
        self.hist_queue_wait.observe(result.queued_s, weights_version=v)
        if result.tokens is not None and result.decode_s > 0:
            self.hist_ms_per_token.observe(
                1e3 * result.decode_s / max(len(result.tokens), 1),
                weights_version=v)
        tr = result.trace
        if tr is not None:
            prefill = sum(s["total_s"] for s in tr.get("spans", ())
                          if s.get("name") == "prefill_admit")
            if prefill > 0:
                self.hist_prefill.observe(prefill, weights_version=v)

    def _on_decoded(self, handle: S.RequestHandle,
                    result: S.Result) -> None:
        if self.post is not None:
            # latency is recorded by the postprocess stage's on_fulfill,
            # AFTER VAE/CLIP time lands in total_s — the percentiles must
            # describe what the caller actually waited for
            self.post.submit(handle, result)
        else:
            tr = getattr(handle, "trace", None)
            if tr is not None and result.trace is None:
                # summarize before the histogram feed (same rule as
                # PostProcessor._fulfill): _record_latency reads
                # result.trace for the prefill span
                result.trace = tr.summary()
            self._record_latency(result)
            handle.fulfill(result)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InferenceServer":
        """Claim the backend (deadline-bounded, retried with backoff) and
        launch the engine + postprocess threads."""
        from dalle_pytorch_tpu.resilience import retry as rretry

        def claim(attempt):
            from dalle_pytorch_tpu.resilience import faults
            faults.maybe_activate_from_env()
            faults.on_backend_init(attempt)
            import jax
            return jax.devices()

        policy = rretry.RetryPolicy(
            max_attempts=max(self.init_retries, 1),
            deadline_s=self.init_deadline_s or None)
        rretry.retry_with_backoff(
            claim, policy, label="serve_backend_init",
            on_event=(lambda rec: self.metrics.resilience(
                rec.get("kind", "bringup_retry"),
                **{k: v for k, v in rec.items()
                   if k not in ("time", "event", "kind")})
            ) if self.metrics is not None else None)

        if self.post is not None:
            self.post.start()
        if self._is_set:
            self.engine.start()     # per-replica threads + supervisor
            if self.autoscaler is not None:
                self.autoscaler.start()
        else:
            self._thread = threading.Thread(
                target=self.engine.run, args=(self._stop,), daemon=True,
                name="serve-engine")
            self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Close the queue (a submit racing shutdown gets a typed
        ``QueueClosed`` instead of landing after the drain and hanging
        its caller), stop the engine(s) — the replica path joins EVERY
        replica thread with its share of the deadline, and a replica
        outliving its join is fenced so it cannot fulfil or requeue
        later — then drain the shared queue ONCE and cancel everything
        still queued AND everything mid-decode in a slot (typed results
        — the no-hangs contract holds through shutdown for admitted
        requests too), then drain the postprocess stage. The drain runs
        AFTER the engines stop, so a straggler's late requeue lands on
        the drained queue and is fulfilled ``cancelled`` on the spot
        instead of stranding its caller."""
        self.queue.close()
        self._stop.set()
        if self.autoscaler is not None:
            self.autoscaler.close()     # no reshapes during teardown
        if self._is_set:
            self.engine.close(timeout)
        elif self._thread is not None:
            self._thread.join(timeout)
        for handle in self.queue.drain():
            handle.fulfill(S.Result(
                status=S.CANCELLED,
                request_id=handle.request.request_id,
                reason="server shutdown"))
        # after the engine thread stopped: slots still holding requests
        # would otherwise leave their callers blocked in result()
        # (the replica path cancelled its in-slot handles in close())
        if not self._is_set:
            self.engine.cancel_active("server shutdown")
        if self.post is not None:
            self.post.close(timeout)

    # -- the Python API -----------------------------------------------------

    def submit(self, codes, *, seed: int = 0, temperature: float = 1.0,
               filter_thres: float = 0.5, top_p: float = 0.0,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               cfg_scale: Optional[float] = None,
               tenant: str = "",
               stream: bool = False,
               n_samples: int = 1,
               image_seq_len_override: int = 0,
               sinks: Optional[list] = None):
        """Enqueue one generation request. Raises a typed, structured
        ``scheduler.ServeRejected`` subclass: ``QueueFull`` on
        backpressure, ``InvalidRequest`` for an empty or over-long
        prompt, ``QueueClosed`` after ``close()``. ``cfg_scale``
        (default: the server's ``default_cfg_scale``) > 0 samples with
        classifier-free guidance — the engine runs a cond/uncond slot
        pair for this request alone; no dedicated engine needed.

        ``stream=True`` attaches a live ``TokenSink`` (the returned
        handle's ``.sink``) fed every harvested chunk; ``n_samples>1``
        admits a best-of-N group and returns a ``GroupFuture`` (handle-
        compatible) whose result carries the CLIP-ranked sample set;
        ``image_seq_len_override`` caps the generated grid for a
        train-free short-resolution draft. All three compose.

        ``sinks`` lets an upstream tier (the gateway) supply its own
        pre-built TokenSinks — one per sample — instead of this server
        creating fresh ones: a replayed dispatch then re-feeds the SAME
        client-facing sinks, whose high-water marks dedupe the replay."""
        if cfg_scale is None:
            cfg_scale = self.default_cfg_scale
        if stream and self.isolation == "process":
            # the child's stand-in handle has no sink — tokens live in
            # another interpreter until the result frame lands, so a
            # "stream" would be a lie. Typed refusal, not a silent
            # downgrade to one-shot.
            record = S.structured_event(
                "serve_reject", reason="stream_process_isolation",
                detail="token streaming requires isolation='thread' — "
                       "a child-process engine's harvest loop cannot "
                       "reach this process's sinks")
            self._queue_event(record)
            raise S.InvalidRequest(record)
        request = S.Request(
            codes=tuple(int(c) for c in codes), seed=seed,
            sampling=S.SamplingParams(temperature=temperature,
                                      filter_thres=filter_thres,
                                      top_p=top_p),
            priority=priority, deadline_s=deadline_s,
            cfg_scale=float(cfg_scale), tenant=str(tenant),
            stream=bool(stream), n_samples=int(n_samples),
            image_seq_len_override=int(image_seq_len_override))
        if request.n_samples > 1:
            from dalle_pytorch_tpu.serve import fanout
            group = fanout.submit_group(
                self.queue, request, metrics=self.metrics,
                max_events=self.stream_max_events, sinks=sinks)
            with self._stream_lock:
                self._groups.append(group)
                if group.sink is not None:
                    self._streams.append(group.sink)
            return group
        sink = sinks[0] if sinks else None
        if request.stream and sink is None:
            from dalle_pytorch_tpu.serve.stream import TokenSink
            sink = TokenSink(max_events=self.stream_max_events,
                             metrics=self.metrics)
        handle = self.queue.submit(request, sink=sink)
        if sink is not None:
            sink.request_id = handle.request.request_id
            with self._stream_lock:
                self._streams.append(sink)
        return handle

    def _sweep_streams(self) -> None:
        """Retire finished streams/groups from the live registries and
        bank each completed group's COW page dividend — called from
        ``stats()`` so the gauges are current at every scrape without a
        dedicated sweeper thread."""
        from dalle_pytorch_tpu.serve import fanout
        with self._stream_lock:
            self._streams = [s for s in self._streams if not s.done]
            still = []
            for g in self._groups:
                if not g.done():
                    still.append(g)
                    continue
                self.groups_completed += 1
                if self._cow_sharing:
                    self.fanout_pages_saved += fanout.group_pages_saved(
                        g.request.n_samples, len(g.request.codes),
                        self._page_size)
            self._groups = still

    def generate(self, codes, timeout: Optional[float] = None,
                 **kwargs) -> S.Result:
        """Synchronous convenience: submit + wait."""
        return self.submit(codes, **kwargs).result(timeout)

    def engine_alive(self) -> bool:
        """True while the serving loop is live (or before start). For a
        replica set: at least ONE replica serving — the set degrades,
        it does not die with a survivor standing."""
        if self._is_set:
            return self.engine.alive()
        return self._thread is None or self._thread.is_alive()

    def health(self) -> dict:
        """The /healthz body: overall liveness plus, for a replica set,
        per-replica state (``running``/``broken``/``drained``,
        heartbeat age) — ``ok`` is False (HTTP 503) only when EVERY
        replica is dead."""
        from dalle_pytorch_tpu.parallel.serve_specs import SERVE_AXIS
        out = {"ok": self.engine_alive(),
               # mesh observability (/healthz satellite): how many
               # devices each replica's engine spans
               "devices_per_replica": self.mesh_devices,
               "mesh_shape": ({SERVE_AXIS: self.mesh_devices}
                              if self.mesh_devices > 1 else None)}
        if self._is_set:
            out["replicas"] = self.engine.replica_states()
            out["weights_version"] = self.engine.weights_version
            out["upgrading"] = self.engine._upgrading
        return out

    # -- the operator scale surface (POST /admin/scale) ---------------------

    def scale(self, op: str, **kwargs) -> dict:
        """One operator reshape: ``add`` / ``remove`` / ``drain`` /
        ``undrain`` / ``upgrade`` / ``status``, delegated to the
        replica set's elastic API. Raises the set's typed errors
        (``ScaleError`` for illegal transitions, ``UpgradeAborted``
        for a failed rollout) — the HTTP facade maps them to status
        codes, Python callers catch them directly."""
        from dalle_pytorch_tpu.serve import replica as R
        from dalle_pytorch_tpu.utils.metrics import structured_event
        if not self._is_set:
            raise R.ScaleError(structured_event(
                "serve_scale_reject", op=op,
                reason="not_a_replica_set"))
        rs = self.engine
        if op == "add":
            index = rs.add_replica(role=str(kwargs.get("role", "both")))
            return {"op": op, "replica": index,
                    "replicas": rs.n_replicas}
        if op == "remove":
            index = int(kwargs["replica"])
            n = rs.remove_replica(index,
                                  drain=bool(kwargs.get("drain", True)))
            return {"op": op, "replica": index, "reclaimed": n,
                    "replicas": rs.n_replicas}
        if op == "drain":
            index = int(kwargs["replica"])
            return {"op": op, "replica": index,
                    "reclaimed": rs.drain_replica(index)}
        if op == "undrain":
            index = int(kwargs["replica"])
            return {"op": op, "replica": index,
                    "ok": rs.undrain_replica(index)}
        if op == "upgrade":
            ckpt = kwargs.get("ckpt")
            version = kwargs.get("version") or str(ckpt)
            if ckpt is None:
                raise R.ScaleError(structured_event(
                    "serve_scale_reject", op=op,
                    reason="upgrade_needs_ckpt"))
            up = dict(version=str(version),
                      canaries=int(kwargs.get("canaries", 2)))
            if rs.worker_ckpt is not None:
                # checkpoint-path attach: the PATH is the upgrade —
                # each worker loads + validates it locally
                up["ckpt"] = str(ckpt)
            else:
                if self.load_weights is None:
                    raise R.ScaleError(structured_event(
                        "serve_scale_reject", op=op,
                        reason="no_weight_loader",
                        detail="server built without load_weights; "
                               "pass params via the Python API"))
                try:
                    up["params"] = self.load_weights(str(ckpt))
                except Exception as e:  # noqa: BLE001 — a wrong or
                    # torn checkpoint path is the MOST likely operator
                    # mistake; it must answer as a typed refusal (the
                    # fleet untouched), never escape the HTTP handler
                    raise R.ScaleError(structured_event(
                        "serve_scale_reject", op=op,
                        reason="weight_load_failed", ckpt=str(ckpt),
                        error=repr(e))) from e
            record = rs.rolling_upgrade(**up)
            self.weights_version = rs.weights_version
            return {"op": op, **record}
        if op == "status":
            return {"op": op, "replicas": rs.replica_states(),
                    "weights_version": rs.weights_version,
                    "upgrading": rs._upgrading,
                    "max_replicas": rs.max_replicas,
                    "scale_outs": rs.scale_outs,
                    "scale_ins": rs.scale_ins,
                    "upgrades": rs.upgrades}
        raise R.ScaleError(structured_event(
            "serve_scale_reject", op=op, reason="unknown_op"))

    def stats(self) -> dict:
        out = self.engine.stats()
        if self._is_set:
            # drain the set's migration wall-time samples into the
            # exposition histogram (the set records, the server exposes)
            samples = self.engine.migration_seconds
            while samples:
                self.hist_migration.observe(samples.pop(0))
        e2e_ps = self.hist_e2e.percentiles((0.50, 0.95, 0.99))
        out.update({
            "requests_submitted": self.queue.submitted,
            # the histogram windows are the ONE latency source of truth
            # (the same samples /metrics exposes and latency_ms reads);
            # one collect+sort per family covers every quantile below
            "p50_latency_s": round(e2e_ps[0.50], 4),
            "p95_latency_s": round(e2e_ps[0.95], 4),
            # operator-facing percentiles off the sliding histogram
            # windows (obs/registry.py) — until now these existed only
            # inside bench sweeps, invisible to a running fleet
            "latency_ms": {
                "e2e": {f"p{int(q * 100)}": round(1e3 * e2e_ps[q], 3)
                        for q in (0.50, 0.95, 0.99)},
                "queue_wait": self.hist_queue_wait.percentiles_ms(),
            },
            "postprocess_pending": (self.post.pending()
                                    if self.post is not None else 0),
        })
        # streaming & fan-out surface (ISSUE 20 satellite): live gauges
        # from the swept registries, lifetime counters from the stages
        self._sweep_streams()
        with self._stream_lock:
            out.update({
                "streams_active": len(self._streams),
                "groups_in_flight": len(self._groups),
                "groups_completed": self.groups_completed,
                "fanout_pages_saved": self.fanout_pages_saved,
            })
        out["preview_frames"] = (self.post.preview_frames
                                 if self.post is not None else 0)
        out["preview_drops"] = (self.post.preview_drops
                                if self.post is not None else 0)
        return out

    # -- /metrics (Prometheus text exposition) ------------------------------

    # (stats_key, metric name, help) — counters are lifetime-monotonic
    # engine/set counters; gauges are point-in-time. Keys absent from a
    # given shape's stats (dense vs paged, single vs set) simply don't
    # render — the catalog is the UNION, docs/OBSERVABILITY.md.
    _COUNTER_METRICS = (
        ("requests_submitted", "dalle_serve_requests_submitted_total",
         "Requests accepted by the admission queue"),
        ("completed", "dalle_serve_requests_completed_total",
         "Requests decoded to completion"),
        ("expired", "dalle_serve_requests_expired_total",
         "Requests that exceeded their deadline (queued or decoding)"),
        ("rejected", "dalle_serve_requests_rejected_total",
         "Typed submit-time rejections (queue full / invalid / closed)"),
        ("tokens_decoded", "dalle_serve_tokens_decoded_total",
         "Distinct delivered image tokens (replay-safe accounting)"),
        ("decode_steps", "dalle_serve_decode_steps_total",
         "Fused decode steps dispatched (chunks x K)"),
        ("harvests", "dalle_serve_harvests_total",
         "Emit-ring device_gets (the only steady-state host syncs)"),
        ("evicted", "dalle_serve_evicted_total",
         "Paged-pool evictions (victims replay token-exact)"),
        ("requeued", "dalle_serve_requeued_total",
         "Requeues from eviction/page-defer/failover"),
        ("prefix_hits", "dalle_serve_prefix_hits_total",
         "Warm prefix-cache admissions (zero prefill FLOPs)"),
        ("failovers", "dalle_serve_failovers_total",
         "Replica fence+reclaim+replay cycles"),
        ("reclaimed", "dalle_serve_reclaimed_total",
         "Requests reclaimed from fenced replicas for replay"),
        ("bringup_failures", "dalle_serve_bringup_failures_total",
         "Replica bring-up attempts that failed (circuit breaker)"),
        ("scale_outs", "dalle_serve_scale_outs_total",
         "Elastic scale-out actions"),
        ("scale_ins", "dalle_serve_scale_ins_total",
         "Elastic scale-in actions"),
        ("upgrades", "dalle_serve_upgrades_total",
         "Completed rolling weight upgrades"),
        ("migrations", "dalle_serve_migrations_total",
         "Live slot migrations completed (drain/scale-in/upgrade/roles)"),
        ("migrate_fallbacks", "dalle_serve_migrate_fallbacks_total",
         "Migrations that fell back to deterministic replay"),
        ("migrated_tokens_saved",
         "dalle_serve_migrated_tokens_saved_total",
         "Tokens live migration avoided re-decoding"),
        ("profiles_taken", "dalle_serve_profiles_taken_total",
         "Completed POST /admin/profile captures"),
        ("reaped", "dalle_serve_reaped_total",
         "Slots freed because the handle terminated externally "
         "(stream disconnect, group cancel, hedge loser)"),
        ("preview_frames", "dalle_serve_preview_frames_total",
         "Progressive preview frames decoded and delivered"),
        ("groups_completed", "dalle_serve_groups_completed_total",
         "Best-of-N sample groups assembled to a ranked result"),
        ("fanout_pages_saved", "dalle_serve_fanout_pages_saved_total",
         "KV pages COW prompt sharing saved across completed groups"),
    )
    _GAUGE_METRICS = (
        ("queue_depth", "dalle_serve_queue_depth",
         "Requests waiting in the admission queue(s)"),
        ("active_slots", "dalle_serve_active_slots",
         "Slots currently decoding"),
        ("num_slots", "dalle_serve_num_slots",
         "Total decode slots across live replicas"),
        ("alive_replicas", "dalle_serve_alive_replicas",
         "Replicas currently serving"),
        ("replicas", "dalle_serve_replicas",
         "Replicas in the set (retired excluded)"),
        ("pages_in_use", "dalle_serve_pages_in_use",
         "Physical KV pages mapped (shared pages counted once)"),
        ("pages_free", "dalle_serve_pages_free",
         "KV pages on the free list"),
        ("kv_hbm_bytes", "dalle_serve_kv_hbm_bytes",
         "Resident HBM bytes of the KV store"),
        ("postprocess_pending", "dalle_serve_postprocess_pending",
         "Completions queued for VAE/CLIP postprocess"),
        ("flight_events", "dalle_serve_flight_events",
         "Records currently retained in the flight ring(s)"),
        ("mean_occupancy", "dalle_serve_mean_occupancy",
         "Mean busy slots per dispatched decode step"),
        ("upgrading", "dalle_serve_upgrading",
         "1 while a rolling upgrade owns the fleet"),
        ("profile_active", "dalle_serve_profile_active",
         "1 while a jax.profiler capture is in flight"),
        ("streams_active", "dalle_serve_streams_active",
         "SSE/token streams currently open (a group counts once)"),
        ("groups_in_flight", "dalle_serve_groups_in_flight",
         "Best-of-N sample groups still decoding"),
    )

    def metrics_text(self) -> str:
        """The ``GET /metrics`` page: counters/gauges projected from
        the live /stats dicts (per-replica samples labeled
        ``replica``/``weights_version``/``state``) plus the latency
        histograms. Built per scrape — scrape cost is one stats() walk
        and string assembly, no device syncs."""
        stats = self.stats()
        counters = [(name, help_text, [(None, stats[key])])
                    for key, name, help_text in self._COUNTER_METRICS
                    if stats.get(key) is not None]
        gauges = [(name, help_text, [(None, stats[key])])
                  for key, name, help_text in self._GAUGE_METRICS
                  if stats.get(key) is not None]
        # identity: which weights generation the fleet serves
        version = stats.get("weights_version", self.weights_version)
        gauges.append(("dalle_serve_info",
                       "Serving identity (labels carry the facts)",
                       [({"weights_version": version,
                          "kv": str(stats.get("kv", "")),
                          "isolation": str(stats.get("isolation",
                                                     "thread"))}, 1)]))
        per = stats.get("per_replica") or ()
        if per:
            def rep_samples(key):
                return [({"replica": rec["replica"],
                          "weights_version": rec.get("weights_version",
                                                     ""),
                          "state": rec.get("state", "")}, rec.get(key))
                        for rec in per]
            counters.append((
                "dalle_serve_replica_tokens_decoded_total",
                "Per-replica tokens decoded (live engines only)",
                rep_samples("tokens_decoded")))
            counters.append((
                "dalle_serve_replica_completed_total",
                "Per-replica completed requests",
                rep_samples("completed")))
            gauges.append((
                "dalle_serve_replica_active_slots",
                "Per-replica busy slots", rep_samples("active_slots")))
            gauges.append((
                "dalle_serve_replica_queued",
                "Per-replica routed-but-not-decoding requests",
                rep_samples("queued")))
            gauges.append((
                "dalle_serve_replica_up",
                "1 while the replica is in the running state",
                [({"replica": rec["replica"],
                   "weights_version": rec.get("weights_version", "")},
                  1 if rec.get("state") == "running" else 0)
                 for rec in per]))
        return self.registry.render(counters=counters, gauges=gauges)

    # -- /debug/events (the flight recorder) --------------------------------

    def debug_events(self) -> dict:
        """Everything the flight recorder holds, one endpoint: the
        set-level ring (scale/upgrade/autoscale lifecycle + fence
        events with embedded victim dumps), per-replica rings, and the
        last dump per fenced replica index."""
        if self._is_set:
            return self.engine.debug_events()
        fl = getattr(self.engine, "flight", None)
        return {"server": fl.dump() if fl is not None else [],
                "replicas": {}, "fenced": {}}

    # -- POST /admin/profile (serve-side jax.profiler capture) --------------

    def profile(self, log_dir: Optional[str] = None, chunks: int = 8,
                replica: int = 0) -> dict:
        """Arm a ``jax.profiler`` capture over the next ``chunks`` fused
        decode chunks of one engine (``Engine.request_profile``).
        ``log_dir`` defaults to the server's ``profile_dir``
        (``serve_dalle --profile_dir``); neither set is a typed
        refusal. Process-isolated replicas are typed-refused too — the
        child's programs run in another interpreter, where this
        process's profiler cannot see."""
        from dalle_pytorch_tpu.serve.engine import ProfileError
        log_dir = log_dir or self.profile_dir
        if not log_dir:
            raise ProfileError(S.structured_event(
                "serve_profile_reject", reason="no_profile_dir",
                detail="pass 'dir' in the request body or start the "
                       "server with --profile_dir"))
        if self._is_set:
            if self.engine.isolation == "process":
                raise ProfileError(S.structured_event(
                    "serve_profile_reject",
                    reason="process_isolation",
                    detail="a child-process engine's programs run in "
                           "another interpreter; profile it from the "
                           "worker host (isolation=thread supports "
                           "in-server capture)"))
            replica = int(replica)
            if not 0 <= replica < len(self.engine.replicas) \
                    or self.engine.replicas[replica].engine is None:
                raise ProfileError(S.structured_event(
                    "serve_profile_reject", reason="no_such_replica",
                    replica=replica))
            eng = self.engine.replicas[replica].engine
        else:
            eng = self.engine
        with self._profile_arm_lock:
            if self._is_set:
                # jax.profiler is a PER-PROCESS singleton: in a thread-
                # isolation set every replica engine shares it, so a
                # capture on any sibling must 409 here — the sibling's
                # own per-engine guard can't see it, and a second
                # start_trace would crash that replica's decode step
                for i, r in enumerate(self.engine.replicas):
                    e = r.engine
                    if e is not None and e is not eng \
                            and e.profile_active():
                        raise ProfileError(S.structured_event(
                            "serve_profile_reject",
                            reason="capture_active", replica=i))
            rec = dict(eng.request_profile(str(log_dir), chunks=chunks))
        rec["replica"] = int(replica) if self._is_set else 0
        return rec


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

_HTTP_STATUS = {S.OK: 200, S.REJECTED: 429, S.DEADLINE_EXCEEDED: 504,
                S.CANCELLED: 503, S.ERROR: 500}


def _result_body(result: S.Result) -> dict:
    body = {"status": result.status, "request_id": result.request_id,
            "reason": result.reason, "queued_s": result.queued_s,
            "decode_s": result.decode_s, "total_s": result.total_s}
    if result.weights_version:
        # which weight generation decoded these tokens — the rolling-
        # upgrade contract's caller-visible half (byte-identical per
        # version), so an HTTP client can audit a mid-upgrade mix
        body["weights_version"] = result.weights_version
    if result.trace is not None:
        # the span-timeline summary (obs/trace.py): where this
        # request's milliseconds went, replay edges included
        body["trace"] = result.trace
    if result.tokens is not None:
        body["tokens"] = [int(t) for t in result.tokens]
    if result.image is not None:
        # pixel grids are bulky as JSON; ship shape + the PNG-side is the
        # CLI's job (cli/serve.py --results_dir). Scores ride along.
        body["image_shape"] = list(result.image.shape)
    if result.clip_score is not None:
        body["clip_score"] = result.clip_score
    if result.samples is not None:
        # best-of-N: the ranked member set, best first — the top-level
        # fields above already describe the winner, so a caller that
        # ignores this key still gets best-of-N semantics for free
        body["samples"] = [_result_body(r) for r in result.samples]
    return body


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 8000,
                     request_timeout_s: float = 600.0) -> ThreadingHTTPServer:
    """An HTTP facade over ``server``. POST /generate blocks the client
    connection until its request completes (the threaded stdlib server
    gives each connection its own thread; concurrency is the engine's
    slot pool, not the HTTP layer)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):    # quiet: metrics are the record
            pass

        def _send(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_text(self, code: int, text: str, ctype: str) -> None:
            data = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                # health must reflect the serving loop(s), not just
                # this HTTP thread — and for a replica set, per-replica
                # liveness with 503 only when ALL replicas are dead
                body = server.health()
                self._send(200 if body["ok"] else 503, body)
            elif self.path == "/stats":
                self._send(200, server.stats())
            elif self.path == "/metrics":
                # Prometheus text exposition (obs/registry.py): the
                # scrape-able twin of /stats plus the latency
                # histograms (docs/OBSERVABILITY.md metric catalog)
                self._send_text(
                    200, server.metrics_text(),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/debug/events":
                # the flight recorder: last-N structured events + span
                # records per replica, always on — the one endpoint a
                # post-incident "why did p95 spike" starts from
                self._send(200, server.debug_events())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def _admin_scale(self):
            """POST /admin/scale — the authenticated operator reshape
            endpoint (docs/SERVING.md 'Elastic fleet'): {"op": "add" |
            "remove" | "drain" | "undrain" | "upgrade" | "status",
            ...}. 401 without the admin token (Bearer or
            X-Admin-Token), 409 with the structured record for a typed
            ScaleError/UpgradeAborted — an illegal transition is a
            refusal the operator can read, never a partial state."""
            from dalle_pytorch_tpu.serve import auth
            from dalle_pytorch_tpu.serve import replica as R
            if not auth.check_http(self.headers, server.admin_token):
                self._send(401, {"error": "bad admin token"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(f"body must be a JSON object, "
                                     f"got {type(req).__name__}")
                op = str(req.pop("op"))
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"need a JSON body with "
                                          f"'op': {e}"})
                return
            try:
                self._send(200, server.scale(op, **req))
            except (R.ScaleError, R.UpgradeAborted) as e:
                self._send(409, e.record)
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})

        def _admin_profile(self):
            """POST /admin/profile — authenticated serve-side profiler
            capture: {"dir": ..., "chunks": K, "replica": i}, all
            optional (``dir`` falls back to --profile_dir). 401 without
            the admin token; 409 with the structured record while a
            capture is already active (or the target can't be
            profiled) — kernel tuning on a real chip is one curl away,
            and two operators can't trample each other's traces."""
            from dalle_pytorch_tpu.serve import auth
            from dalle_pytorch_tpu.serve.engine import ProfileError
            if not auth.check_http(self.headers, server.admin_token):
                self._send(401, {"error": "bad admin token"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(f"body must be a JSON object, "
                                     f"got {type(req).__name__}")
                rec = server.profile(
                    log_dir=req.get("dir"),
                    chunks=int(req.get("chunks", 8)),
                    replica=int(req.get("replica", 0)))
            except ProfileError as e:
                self._send(409, e.record)
                return
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})
                return
            self._send(200, rec)

        def do_POST(self):
            if self.path == "/admin/scale":
                self._admin_scale()
                return
            if self.path == "/admin/profile":
                self._admin_profile()
                return
            if self.path != "/generate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                codes = req.get("codes")
                if codes is None and "caption" in req:
                    if server.encode is None:
                        raise ValueError("server has no vocab; send "
                                         "'codes', not 'caption'")
                    codes = server.encode(req["caption"])
                if not codes:
                    raise ValueError("need non-empty 'codes' or 'caption'")
                kwargs = {k: req[k] for k in
                          ("seed", "temperature", "filter_thres", "top_p",
                           "priority", "deadline_s", "cfg_scale",
                           "stream", "n_samples",
                           "image_seq_len_override")
                          if k in req}
                handle = server.submit(codes, **kwargs)
            except S.InvalidRequest as e:
                self._send(400, e.record)       # caller error, not load
                return
            except S.QueueClosed as e:
                self._send(503, e.record)       # shutting down
                return
            except S.ServeRejected as e:
                self._send(429, e.record)       # backpressure
                return
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})
                return
            sink = getattr(handle, "sink", None)
            if sink is not None:
                self._stream_sse(handle, sink)
                return
            try:
                result = handle.result(timeout=request_timeout_s)
            except TimeoutError as e:
                self._send(504, {"error": str(e)})
                return
            self._send(_HTTP_STATUS.get(result.status, 500),
                       _result_body(result))

        def _stream_sse(self, handle, sink) -> None:
            """The streaming response: server-sent events over a
            chunkless HTTP/1.1 body (no Content-Length; the connection
            close delimits the stream — EventSource-compatible).
            Heartbeat comments keep idle proxies from timing the
            stream out; a torn connection (client gone) cancels the
            request/group on the spot, so the engine's done-handle
            reap frees its slots and pages instead of decoding into
            the void."""
            from dalle_pytorch_tpu.serve import stream as stream_mod
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            try:
                for ev in sink.events(heartbeat_s=5.0):
                    self.wfile.write(stream_mod.sse_bytes(ev))
                    self.wfile.flush()
                # the terminal frame: the assembled result (ranked
                # samples for a group), so an SSE client needs no
                # second round-trip to fetch what it just watched
                result = handle.result(timeout=request_timeout_s)
                self.wfile.write(stream_mod.sse_bytes(
                    {"event": "result", **_result_body(result)}))
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                handle.fulfill(S.Result(
                    status=S.CANCELLED,
                    request_id=handle.request.request_id,
                    reason="client disconnected mid-stream"))
            except TimeoutError:
                pass    # stream already delivered everything it had

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    return httpd


def serve_http(server: InferenceServer, host: str = "127.0.0.1",
               port: int = 8000) -> None:
    """Blocking HTTP loop (cli/serve.py's main); Ctrl-C shuts down the
    pipeline cleanly."""
    httpd = make_http_server(server, host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        server.close()
