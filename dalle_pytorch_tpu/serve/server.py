"""Serving front-end: the threaded Python API and the stdlib HTTP server.

``InferenceServer`` wires the three pipeline stages together —
``scheduler.RequestQueue`` (admission) -> ``engine.Engine`` (slot-batched
decode, its own thread) -> ``postprocess.PostProcessor`` (VAE/CLIP, its
own thread) — and owns their lifecycle. Backend bring-up goes through the
SAME deadline/backoff/jitter discipline as every other entry point
(``resilience.retry``): a wedged TPU claim surfaces as a structured
``BringupError`` instead of a hung server.

Two call surfaces:
  * Python: ``submit(codes, ...) -> RequestHandle`` / ``stats()`` — what
    tests, the bench, and embedders use;
  * HTTP (``serve_http``): POST /generate {"codes": [...] | "caption":
    "...", sampling knobs...} blocks for the result (429 on queue-full,
    504 on deadline, both with the structured record as the JSON body);
    GET /stats and /healthz for operators. The stdlib ThreadingHTTPServer
    is deliberate — one dependency-free front-end; a production mesh
    would sit a real gateway in front of the same Python API.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from dalle_pytorch_tpu.serve import engine as engine_mod
from dalle_pytorch_tpu.serve import postprocess as post_mod
from dalle_pytorch_tpu.serve import scheduler as S


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile; [] -> 0.0 (no completed requests yet)."""
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


class InferenceServer:
    """Continuous-batching text->image service. ``replicas=1`` (the
    default) runs one engine on one thread; ``replicas=N`` fronts N
    supervised engine replicas with the same shared queue through
    ``serve.replica.ReplicaSet`` — replica crash/hang/drain fails over
    with zero lost requests via deterministic replay, and capacity loss
    degrades to typed ``QueueFull`` backpressure (docs/SERVING.md
    'Replica set & failover'). ``isolation='process'`` additionally
    runs each replica's engine in a supervised child process, so a
    SIGSEGV/SIGKILL/OOM kill of one replica cannot take the server
    down (docs/SERVING.md 'Process isolation'); /healthz then reports
    per-replica PID, restart count, last exit signal, and child RSS,
    503 still only when ALL replicas are dead."""

    def __init__(self, params: dict, vae_params: dict, cfg, *,
                 num_slots: int = 4, queue_depth: int = 64,
                 chunk_steps: int = 8,
                 prefill_buckets=None,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 paged_attn: str = "gather",
                 sparse_reads: bool = False,
                 prefix_cache: bool = False,
                 default_cfg_scale: float = 0.0,
                 replicas: int = 1,
                 mesh_devices: int = 1,
                 weights_version: str = "0",
                 max_replicas: int = 0,
                 autoscale=None,
                 admin_token: Optional[str] = None,
                 load_weights: Optional[Callable] = None,
                 heartbeat_s: float = 5.0,
                 isolation: str = "thread",
                 child_rss_limit_mb: int = 0,
                 transport: str = "pipe",
                 worker_endpoint: str = "127.0.0.1:0",
                 worker_cmd: Optional[str] = None,
                 attach_token: Optional[str] = None,
                 worker_ckpt: Optional[str] = None,
                 worker_use_ema: bool = False,
                 worker_quantize: str = "none",
                 clip_params: Optional[dict] = None, clip_cfg=None,
                 decode_images: bool = True,
                 metrics=None, log_every: int = 50,
                 encode: Optional[Callable[[str], List[int]]] = None,
                 init_deadline_s: float = 0.0, init_retries: int = 3):
        self.cfg = cfg
        self.metrics = metrics
        self.encode = encode
        # server-wide guidance default: a request that doesn't carry
        # its own cfg_scale samples with this one (0 = unguided)
        self.default_cfg_scale = float(default_cfg_scale)
        if self.default_cfg_scale < 0:
            raise ValueError(f"default_cfg_scale must be >= 0, got "
                             f"{default_cfg_scale}")
        self.init_deadline_s = init_deadline_s
        self.init_retries = init_retries
        self.replicas = int(replicas)
        # the elastic operator surface (docs/SERVING.md 'Elastic
        # fleet'): POST /admin/scale authenticates against this token
        # (generated when the caller supplies none — printed by the
        # CLI, never guessable), add/remove/drain/upgrade delegate to
        # the replica set, and an AutoscalePolicy drives the same
        # calls off the load signals. A single-replica server with
        # autoscale or a max_replicas headroom cap still fronts a
        # ReplicaSet — elasticity needs supervised slots to grow into.
        import secrets as _secrets
        self.admin_token = admin_token or _secrets.token_hex(16)
        self.autoscale_policy = autoscale
        self.autoscaler = None
        self.load_weights = load_weights
        self.weights_version = str(weights_version)
        self.max_replicas = int(max_replicas)
        self._is_set = (self.replicas > 1 or autoscale is not None
                        or self.max_replicas > 1)
        if autoscale is not None:
            # the policy caps and the set cap must agree, or the
            # autoscaler would ask for replicas the set typed-rejects
            self.max_replicas = max(self.max_replicas,
                                    autoscale.max_replicas)
        self.mesh_devices = int(mesh_devices)
        if self.mesh_devices < 1:
            raise ValueError(f"mesh_devices must be >= 1, got "
                             f"{mesh_devices}")
        if worker_ckpt is not None and transport != "socket":
            # same silent-misconfiguration hazard as worker_cmd: the
            # operator believes workers load locally when they don't.
            # (socket itself already implies process isolation and
            # replicas >= 2 via the checks below)
            raise ValueError(
                "worker_ckpt requires transport='socket' — its point "
                "is that a worker loads the checkpoint from its OWN "
                "host's store instead of receiving params over a pipe")
        if isolation == "process" and self.replicas < 2:
            # process isolation exists to keep the SET alive through a
            # child death; a 1-replica process set is legal for the
            # ReplicaSet API (restart-with-replay), but the server's
            # contract is replicas>1 — fail loudly instead of serving a
            # shape the operator almost certainly didn't mean
            raise ValueError("isolation='process' requires replicas >= 2")
        if transport != "pipe" and isolation != "process":
            # a transport only exists between a parent and worker
            # processes; silently ignoring the flag would let an
            # operator believe they were host-isolated when they weren't
            raise ValueError(
                f"transport={transport!r} requires isolation='process'")
        if worker_cmd is not None and self.replicas < 2:
            # the single-engine path would drop the launcher command on
            # the floor — same silent-misconfiguration hazard as above
            raise ValueError("worker_cmd requires replicas >= 2 with "
                             "isolation='process' and "
                             "transport='socket'")
        self.isolation = str(isolation)

        self.queue = S.RequestQueue(
            max_depth=queue_depth,
            # a prompt the slot pool can't hold is rejected HERE (typed
            # InvalidRequest / HTTP 400), before it can reach the engine
            max_prompt_len=cfg.text_seq_len,
            on_event=(lambda rec: metrics.event(**rec))
            if metrics is not None else None)
        self.post = None
        if decode_images:
            self.post = post_mod.PostProcessor(
                params, vae_params, cfg, clip_params=clip_params,
                clip_cfg=clip_cfg, metrics=metrics,
                on_fulfill=self._record_latency)
        if self._is_set:
            from dalle_pytorch_tpu.serve import replica as replica_mod
            self.engine = replica_mod.ReplicaSet(
                params, cfg, self.queue, replicas=self.replicas,
                num_slots=num_slots, chunk_steps=chunk_steps,
                prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                prefix_cache=prefix_cache,
                heartbeat_s=heartbeat_s, isolation=isolation,
                child_rss_limit_mb=child_rss_limit_mb,
                transport=transport, worker_endpoint=worker_endpoint,
                worker_cmd=worker_cmd, attach_token=attach_token,
                worker_ckpt=worker_ckpt,
                worker_use_ema=worker_use_ema,
                worker_quantize=worker_quantize,
                devices_per_replica=self.mesh_devices,
                weights_version=self.weights_version,
                max_replicas=self.max_replicas)
            if self.autoscale_policy is not None:
                from dalle_pytorch_tpu.serve.autoscale import Autoscaler
                self.autoscaler = Autoscaler(
                    self.engine, self.autoscale_policy, metrics=metrics)
        elif self.mesh_devices > 1:
            # ONE logical engine pjit-sharded over a device mesh — the
            # serve surface is identical (docs/SERVING.md 'Mesh-sharded
            # engine'), so the single-engine thread loop below drives it
            # unchanged
            import jax

            from dalle_pytorch_tpu.serve.mesh_engine import MeshEngine
            from dalle_pytorch_tpu.parallel import serve_specs as SS
            self.engine = MeshEngine(
                params, cfg, self.queue,
                devices=SS.slice_devices(jax.devices(), 0,
                                         self.mesh_devices),
                num_slots=num_slots,
                chunk_steps=chunk_steps, prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                prefix_cache=prefix_cache,
                weights_version=self.weights_version,
                model_version=self.weights_version)
        else:
            self.engine = engine_mod.Engine(
                params, cfg, self.queue, num_slots=num_slots,
                chunk_steps=chunk_steps, prefill_buckets=prefill_buckets,
                complete=self._on_decoded, metrics=metrics,
                log_every=log_every, quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                prefix_cache=prefix_cache,
                weights_version=self.weights_version,
                model_version=self.weights_version)

        # bounded window: p50/p95 over the last 10k completions — an
        # unbounded list would grow (and re-sort under the lock) forever
        # on a long-lived server
        self._latencies: deque = deque(maxlen=10_000)
        self._lat_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- stage glue ---------------------------------------------------------

    def _record_latency(self, result: S.Result) -> None:
        # successful completions only: mixing in error results (whose
        # wait ends early) would deflate the percentiles exactly when a
        # failing dependency makes the tail matter most
        if not result.ok:
            return
        with self._lat_lock:
            self._latencies.append(result.total_s)

    def _on_decoded(self, handle: S.RequestHandle,
                    result: S.Result) -> None:
        if self.post is not None:
            # latency is recorded by the postprocess stage's on_fulfill,
            # AFTER VAE/CLIP time lands in total_s — the percentiles must
            # describe what the caller actually waited for
            self.post.submit(handle, result)
        else:
            self._record_latency(result)
            handle.fulfill(result)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InferenceServer":
        """Claim the backend (deadline-bounded, retried with backoff) and
        launch the engine + postprocess threads."""
        from dalle_pytorch_tpu.resilience import retry as rretry

        def claim(attempt):
            from dalle_pytorch_tpu.resilience import faults
            faults.maybe_activate_from_env()
            faults.on_backend_init(attempt)
            import jax
            return jax.devices()

        policy = rretry.RetryPolicy(
            max_attempts=max(self.init_retries, 1),
            deadline_s=self.init_deadline_s or None)
        rretry.retry_with_backoff(
            claim, policy, label="serve_backend_init",
            on_event=(lambda rec: self.metrics.resilience(
                rec.get("kind", "bringup_retry"),
                **{k: v for k, v in rec.items()
                   if k not in ("time", "event", "kind")})
            ) if self.metrics is not None else None)

        if self.post is not None:
            self.post.start()
        if self._is_set:
            self.engine.start()     # per-replica threads + supervisor
            if self.autoscaler is not None:
                self.autoscaler.start()
        else:
            self._thread = threading.Thread(
                target=self.engine.run, args=(self._stop,), daemon=True,
                name="serve-engine")
            self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Close the queue (a submit racing shutdown gets a typed
        ``QueueClosed`` instead of landing after the drain and hanging
        its caller), stop the engine(s) — the replica path joins EVERY
        replica thread with its share of the deadline, and a replica
        outliving its join is fenced so it cannot fulfil or requeue
        later — then drain the shared queue ONCE and cancel everything
        still queued AND everything mid-decode in a slot (typed results
        — the no-hangs contract holds through shutdown for admitted
        requests too), then drain the postprocess stage. The drain runs
        AFTER the engines stop, so a straggler's late requeue lands on
        the drained queue and is fulfilled ``cancelled`` on the spot
        instead of stranding its caller."""
        self.queue.close()
        self._stop.set()
        if self.autoscaler is not None:
            self.autoscaler.close()     # no reshapes during teardown
        if self._is_set:
            self.engine.close(timeout)
        elif self._thread is not None:
            self._thread.join(timeout)
        for handle in self.queue.drain():
            handle.fulfill(S.Result(
                status=S.CANCELLED,
                request_id=handle.request.request_id,
                reason="server shutdown"))
        # after the engine thread stopped: slots still holding requests
        # would otherwise leave their callers blocked in result()
        # (the replica path cancelled its in-slot handles in close())
        if not self._is_set:
            self.engine.cancel_active("server shutdown")
        if self.post is not None:
            self.post.close(timeout)

    # -- the Python API -----------------------------------------------------

    def submit(self, codes, *, seed: int = 0, temperature: float = 1.0,
               filter_thres: float = 0.5, top_p: float = 0.0,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               cfg_scale: Optional[float] = None) -> S.RequestHandle:
        """Enqueue one generation request. Raises a typed, structured
        ``scheduler.ServeRejected`` subclass: ``QueueFull`` on
        backpressure, ``InvalidRequest`` for an empty or over-long
        prompt, ``QueueClosed`` after ``close()``. ``cfg_scale``
        (default: the server's ``default_cfg_scale``) > 0 samples with
        classifier-free guidance — the engine runs a cond/uncond slot
        pair for this request alone; no dedicated engine needed."""
        if cfg_scale is None:
            cfg_scale = self.default_cfg_scale
        return self.queue.submit(S.Request(
            codes=tuple(int(c) for c in codes), seed=seed,
            sampling=S.SamplingParams(temperature=temperature,
                                      filter_thres=filter_thres,
                                      top_p=top_p),
            priority=priority, deadline_s=deadline_s,
            cfg_scale=float(cfg_scale)))

    def generate(self, codes, timeout: Optional[float] = None,
                 **kwargs) -> S.Result:
        """Synchronous convenience: submit + wait."""
        return self.submit(codes, **kwargs).result(timeout)

    def engine_alive(self) -> bool:
        """True while the serving loop is live (or before start). For a
        replica set: at least ONE replica serving — the set degrades,
        it does not die with a survivor standing."""
        if self._is_set:
            return self.engine.alive()
        return self._thread is None or self._thread.is_alive()

    def health(self) -> dict:
        """The /healthz body: overall liveness plus, for a replica set,
        per-replica state (``running``/``broken``/``drained``,
        heartbeat age) — ``ok`` is False (HTTP 503) only when EVERY
        replica is dead."""
        from dalle_pytorch_tpu.parallel.serve_specs import SERVE_AXIS
        out = {"ok": self.engine_alive(),
               # mesh observability (/healthz satellite): how many
               # devices each replica's engine spans
               "devices_per_replica": self.mesh_devices,
               "mesh_shape": ({SERVE_AXIS: self.mesh_devices}
                              if self.mesh_devices > 1 else None)}
        if self._is_set:
            out["replicas"] = self.engine.replica_states()
            out["weights_version"] = self.engine.weights_version
            out["upgrading"] = self.engine._upgrading
        return out

    # -- the operator scale surface (POST /admin/scale) ---------------------

    def scale(self, op: str, **kwargs) -> dict:
        """One operator reshape: ``add`` / ``remove`` / ``drain`` /
        ``undrain`` / ``upgrade`` / ``status``, delegated to the
        replica set's elastic API. Raises the set's typed errors
        (``ScaleError`` for illegal transitions, ``UpgradeAborted``
        for a failed rollout) — the HTTP facade maps them to status
        codes, Python callers catch them directly."""
        from dalle_pytorch_tpu.serve import replica as R
        from dalle_pytorch_tpu.utils.metrics import structured_event
        if not self._is_set:
            raise R.ScaleError(structured_event(
                "serve_scale_reject", op=op,
                reason="not_a_replica_set"))
        rs = self.engine
        if op == "add":
            index = rs.add_replica()
            return {"op": op, "replica": index,
                    "replicas": rs.n_replicas}
        if op == "remove":
            index = int(kwargs["replica"])
            n = rs.remove_replica(index,
                                  drain=bool(kwargs.get("drain", True)))
            return {"op": op, "replica": index, "reclaimed": n,
                    "replicas": rs.n_replicas}
        if op == "drain":
            index = int(kwargs["replica"])
            return {"op": op, "replica": index,
                    "reclaimed": rs.drain_replica(index)}
        if op == "undrain":
            index = int(kwargs["replica"])
            return {"op": op, "replica": index,
                    "ok": rs.undrain_replica(index)}
        if op == "upgrade":
            ckpt = kwargs.get("ckpt")
            version = kwargs.get("version") or str(ckpt)
            if ckpt is None:
                raise R.ScaleError(structured_event(
                    "serve_scale_reject", op=op,
                    reason="upgrade_needs_ckpt"))
            up = dict(version=str(version),
                      canaries=int(kwargs.get("canaries", 2)))
            if rs.worker_ckpt is not None:
                # checkpoint-path attach: the PATH is the upgrade —
                # each worker loads + validates it locally
                up["ckpt"] = str(ckpt)
            else:
                if self.load_weights is None:
                    raise R.ScaleError(structured_event(
                        "serve_scale_reject", op=op,
                        reason="no_weight_loader",
                        detail="server built without load_weights; "
                               "pass params via the Python API"))
                try:
                    up["params"] = self.load_weights(str(ckpt))
                except Exception as e:  # noqa: BLE001 — a wrong or
                    # torn checkpoint path is the MOST likely operator
                    # mistake; it must answer as a typed refusal (the
                    # fleet untouched), never escape the HTTP handler
                    raise R.ScaleError(structured_event(
                        "serve_scale_reject", op=op,
                        reason="weight_load_failed", ckpt=str(ckpt),
                        error=repr(e))) from e
            record = rs.rolling_upgrade(**up)
            self.weights_version = rs.weights_version
            return {"op": op, **record}
        if op == "status":
            return {"op": op, "replicas": rs.replica_states(),
                    "weights_version": rs.weights_version,
                    "upgrading": rs._upgrading,
                    "max_replicas": rs.max_replicas,
                    "scale_outs": rs.scale_outs,
                    "scale_ins": rs.scale_ins,
                    "upgrades": rs.upgrades}
        raise R.ScaleError(structured_event(
            "serve_scale_reject", op=op, reason="unknown_op"))

    def stats(self) -> dict:
        with self._lat_lock:
            lats = sorted(self._latencies)
        out = self.engine.stats()
        out.update({
            "requests_submitted": self.queue.submitted,
            "p50_latency_s": round(_percentile(lats, 0.50), 4),
            "p95_latency_s": round(_percentile(lats, 0.95), 4),
            "postprocess_pending": (self.post.pending()
                                    if self.post is not None else 0),
        })
        return out


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

_HTTP_STATUS = {S.OK: 200, S.REJECTED: 429, S.DEADLINE_EXCEEDED: 504,
                S.CANCELLED: 503, S.ERROR: 500}


def _result_body(result: S.Result) -> dict:
    body = {"status": result.status, "request_id": result.request_id,
            "reason": result.reason, "queued_s": result.queued_s,
            "decode_s": result.decode_s, "total_s": result.total_s}
    if result.weights_version:
        # which weight generation decoded these tokens — the rolling-
        # upgrade contract's caller-visible half (byte-identical per
        # version), so an HTTP client can audit a mid-upgrade mix
        body["weights_version"] = result.weights_version
    if result.tokens is not None:
        body["tokens"] = [int(t) for t in result.tokens]
    if result.image is not None:
        # pixel grids are bulky as JSON; ship shape + the PNG-side is the
        # CLI's job (cli/serve.py --results_dir). Scores ride along.
        body["image_shape"] = list(result.image.shape)
    if result.clip_score is not None:
        body["clip_score"] = result.clip_score
    return body


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 8000,
                     request_timeout_s: float = 600.0) -> ThreadingHTTPServer:
    """An HTTP facade over ``server``. POST /generate blocks the client
    connection until its request completes (the threaded stdlib server
    gives each connection its own thread; concurrency is the engine's
    slot pool, not the HTTP layer)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):    # quiet: metrics are the record
            pass

        def _send(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                # health must reflect the serving loop(s), not just
                # this HTTP thread — and for a replica set, per-replica
                # liveness with 503 only when ALL replicas are dead
                body = server.health()
                self._send(200 if body["ok"] else 503, body)
            elif self.path == "/stats":
                self._send(200, server.stats())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def _admin_scale(self):
            """POST /admin/scale — the authenticated operator reshape
            endpoint (docs/SERVING.md 'Elastic fleet'): {"op": "add" |
            "remove" | "drain" | "undrain" | "upgrade" | "status",
            ...}. 401 without the admin token (Bearer or
            X-Admin-Token), 409 with the structured record for a typed
            ScaleError/UpgradeAborted — an illegal transition is a
            refusal the operator can read, never a partial state."""
            import hmac as _hmac

            from dalle_pytorch_tpu.serve import replica as R
            auth = self.headers.get("Authorization", "")
            token = auth[7:] if auth.startswith("Bearer ") \
                else (self.headers.get("X-Admin-Token") or "")
            if not _hmac.compare_digest(token, server.admin_token):
                self._send(401, {"error": "bad admin token"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(f"body must be a JSON object, "
                                     f"got {type(req).__name__}")
                op = str(req.pop("op"))
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": f"need a JSON body with "
                                          f"'op': {e}"})
                return
            try:
                self._send(200, server.scale(op, **req))
            except (R.ScaleError, R.UpgradeAborted) as e:
                self._send(409, e.record)
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})

        def do_POST(self):
            if self.path == "/admin/scale":
                self._admin_scale()
                return
            if self.path != "/generate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                codes = req.get("codes")
                if codes is None and "caption" in req:
                    if server.encode is None:
                        raise ValueError("server has no vocab; send "
                                         "'codes', not 'caption'")
                    codes = server.encode(req["caption"])
                if not codes:
                    raise ValueError("need non-empty 'codes' or 'caption'")
                kwargs = {k: req[k] for k in
                          ("seed", "temperature", "filter_thres", "top_p",
                           "priority", "deadline_s", "cfg_scale")
                          if k in req}
                handle = server.submit(codes, **kwargs)
            except S.InvalidRequest as e:
                self._send(400, e.record)       # caller error, not load
                return
            except S.QueueClosed as e:
                self._send(503, e.record)       # shutting down
                return
            except S.ServeRejected as e:
                self._send(429, e.record)       # backpressure
                return
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})
                return
            try:
                result = handle.result(timeout=request_timeout_s)
            except TimeoutError as e:
                self._send(504, {"error": str(e)})
                return
            self._send(_HTTP_STATUS.get(result.status, 500),
                       _result_body(result))

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    return httpd


def serve_http(server: InferenceServer, host: str = "127.0.0.1",
               port: int = 8000) -> None:
    """Blocking HTTP loop (cli/serve.py's main); Ctrl-C shuts down the
    pipeline cleanly."""
    httpd = make_http_server(server, host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        server.close()
