"""Best-of-N fan-out sampling (docs/SERVING.md "Streaming, fan-out &
variable resolution").

The reference pipeline is sample-then-rerank: draw N candidate images
for one prompt, score each against the prompt with CLIP, keep the
best. This module turns that loop into ONE serving-tier request:
``Request.n_samples = N`` admits a sample *group* — N member requests
sharing the prompt, each with a deterministically derived per-sample
seed — and returns a ``GroupFuture`` whose result is the ranked set.

Cost model: the members share the prompt byte-for-byte, so under the
paged KV layout the prefix cache's refcounted COW sharing makes the
group cost ~1× prompt prefill, not N× — the first member (cold or
warm) populates the shared span, siblings retain it pending and fork
only the boundary page (``pages_shared`` in engine stats proves it).
Determinism: ``sample_seed(seed, i)`` is a pure function, and member
``i`` is an ORDINARY request — byte-identical to a standalone request
submitted with that seed, across layouts, kernels, and KV dtypes —
so eviction replay, failover, and live migration compose with groups
for free: one member replays or migrates without touching siblings.

Group lifecycle is atomic at both ends: admission submits all N
members or none (a mid-group queue reject cancels the already-
admitted prefix before propagating), and completion assembles exactly
one ranked Result once every member reaches a terminal state.
Cancelling the group (client disconnect, gateway sweep) fulfils every
member as cancelled — the engine's done-handle reap then frees their
slots and pages mid-decode instead of generating into the void.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve.stream import TokenSink

_MIX = 0x9E3779B9          # golden-ratio increment (splitmix)


def sample_seed(seed: int, i: int) -> int:
    """The per-sample RNG seed for member ``i`` of a group seeded with
    ``seed``. Index 0 returns ``seed`` itself, so best-of-1 is
    byte-identical to a plain request; higher indices get a 32-bit
    avalanche mix (finalizer from splitmix/murmur) — distinct streams
    from one user-visible seed, reproducible standalone by submitting
    the derived seed directly."""
    i = int(i)
    if i == 0:
        return int(seed)
    x = (int(seed) + i * _MIX) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def group_pages_saved(n_samples: int, prompt_len: int,
                      page_size: int) -> int:
    """KV pages the COW prompt share saves for one completed group,
    versus N independent prefills: each of the N−1 siblings retains
    the leader's whole prompt pages instead of allocating its own
    (the boundary partial page is forked private, so it saves
    nothing). 0 for dense layouts (no pages to share) and for
    singleton groups."""
    n, p = int(n_samples), int(page_size)
    if n <= 1 or p <= 0:
        return 0
    return (n - 1) * (int(prompt_len) // p)


def rank_samples(results: List[S.Result]) -> List[S.Result]:
    """Member results best-first: successful samples before failed
    ones, by CLIP score descending within the successes, original
    sample index as the deterministic tiebreak (covers CLIP-disabled
    deployments, where every score is None)."""
    def key(pair):
        i, r = pair
        score = r.clip_score if r.clip_score is not None else 0.0
        return (0 if r.ok else 1, -float(score), i)
    return [r for _, r in sorted(enumerate(results), key=key)]


class GroupFuture:
    """Handle for one best-of-N group: duck-types the parts of
    ``RequestHandle`` the server and gateway consume (``request``,
    ``done()``, ``result(timeout)``, ``fulfill(result)``), so a group
    rides every existing sweep — deadline, cancel, shutdown —
    unchanged.

    ``result`` blocks until EVERY member is terminal, then assembles
    one ranked Result: the best sample's tokens/image/score at the
    top level (a best-of-N caller that ignores ``samples`` just gets
    the best image), the full ranked member set in ``.samples``.
    ``fulfill`` is the group cancel: first-write-wins like the
    handle it imitates, and fans the terminal result out to every
    live member so their slots and pages come back."""

    def __init__(self, request: S.Request,
                 members: List[S.RequestHandle],
                 sinks: Optional[List[TokenSink]] = None):
        if not members:
            raise ValueError("a sample group needs >= 1 member")
        # the parent request, stamped with the leader's identity: the
        # group is addressed (gateway flights, stats, cancellation) by
        # its first member's request_id
        self.request = dataclasses.replace(
            request,
            request_id=members[0].request.request_id,
            submit_t=members[0].request.submit_t)
        self.members = members
        self.sinks = sinks or []
        self._lock = threading.Lock()
        self._result: Optional[S.Result] = None

    @property
    def sink(self) -> Optional[TokenSink]:
        """Any member sink reads the whole group's multiplexed channel
        — expose the leader's for the SSE writer."""
        return self.sinks[0] if self.sinks else None

    def done(self) -> bool:
        with self._lock:
            if self._result is not None:
                return True
        return all(m.done() for m in self.members)

    def fulfill(self, result: S.Result) -> bool:
        """Group-terminal override — the cancel path (client
        disconnect, gateway deadline sweep, shutdown). Cancels every
        member that hasn't finished; members' own ``fulfill`` closes
        their sinks, so the stream channel still ends cleanly."""
        with self._lock:
            if self._result is not None:
                return False
            self._result = result
        for m in self.members:
            m.fulfill(dataclasses.replace(
                result, request_id=m.request.request_id,
                samples=None))
        return True

    def result(self, timeout: Optional[float] = None) -> S.Result:
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        outs = []
        for m in self.members:
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.0)
            outs.append(m.result(left))   # raises TimeoutError like
            #                               RequestHandle.result
        with self._lock:
            if self._result is not None:
                return self._result       # cancelled while assembling
            ranked = rank_samples(outs)
            best = ranked[0]
            bad = next((r for r in outs if not r.ok), None)
            self._result = S.Result(
                status=S.OK if bad is None else bad.status,
                request_id=self.request.request_id,
                tokens=best.tokens,
                text_tokens=best.text_tokens,
                image=best.image,
                clip_score=best.clip_score,
                reason="" if bad is None else
                       (f"sample {bad.request_id}: "
                        f"{bad.reason or bad.status}"),
                weights_version=best.weights_version,
                queued_s=max(r.queued_s for r in outs),
                decode_s=max(r.decode_s for r in outs),
                total_s=max(r.total_s for r in outs),
                samples=ranked)
            return self._result


def submit_group(queue: S.RequestQueue, request: S.Request, *,
                 metrics=None, max_events: int = 256,
                 sinks: Optional[List[TokenSink]] = None
                 ) -> GroupFuture:
    """Admit one best-of-N group: N member requests (per-sample seeds,
    ``n_samples`` reset to 1 so a member is indistinguishable from a
    standalone request) submitted back-to-back so the prefix cache's
    pending-share window covers the whole set. Admission is atomic —
    if member k is rejected (queue full, closed), the k already-
    admitted members are cancelled before the typed reject propagates,
    so a failed group never leaks half its samples into the engine."""
    n = int(request.n_samples)
    if sinks is not None:
        # an upstream tier's sinks (gateway replay-dedupe path): one
        # per member, already sharing a channel
        if len(sinks) != n:
            raise ValueError(f"sinks must match n_samples: "
                             f"{len(sinks)} != {n}")
        sinks = list(sinks)
    elif request.stream:
        sinks = list(TokenSink.group(n, max_events=max_events,
                                     metrics=metrics))
    else:
        sinks = [None] * n
    members: List[S.RequestHandle] = []
    try:
        for i in range(n):
            member = dataclasses.replace(
                request, seed=sample_seed(request.seed, i),
                n_samples=1, request_id=-1, submit_t=0.0)
            h = queue.submit(member, sink=sinks[i])
            if sinks[i] is not None:
                sinks[i].request_id = h.request.request_id
            members.append(h)
    except Exception:
        for m in members:
            m.fulfill(S.Result(
                status=S.CANCELLED,
                request_id=m.request.request_id,
                reason="group admission failed"))
        raise
    return GroupFuture(request, members,
                       sinks=[s for s in sinks if s is not None])
