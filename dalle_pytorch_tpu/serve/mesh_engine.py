"""Mesh-sharded serving engine: ONE logical Engine pjit-sharded over an
ICI device mesh.

Every serving scale axis so far multiplied ENGINES — replicas (PR 7),
processes (PR 8), hosts (PR 10) — but each engine was still pinned to
one chip, so a DALLE config whose params + paged KV pool exceed a single
device's HBM could not be served at all. ``MeshEngine`` is the missing
axis: the SAME ``Engine`` (same prefill buckets, same fused-K emit-ring
``decode_loop_paged``, same paged KV lifecycle, same ``step_once`` /
``fence`` / ``counters`` / ``progress_snapshot`` supervision surface)
with its params and KV store sharded across a ``jax.sharding.Mesh`` by
the serve partition rules in ``parallel/serve_specs.py``:

  * transformer layer stacks shard DEPTH (ZeRO-style; params HBM 1/m),
  * the KV store — dense slot cache or paged page pool ``(depth,
    num_pages, heads, page_size, dim_head)``, int8 scale pages included
    — shards HEADS (KV HBM 1/m, the term that caps concurrency),
  * embedding/logits tables shard VOCAB,
  * everything the host protocol touches — per-slot decode state, block
    tables, the emit ring — is REPLICATED, so the host side of the
    engine (PageAllocator, admission device_puts, the one explicit
    emit-ring device_get per chunk) is bit-for-bit the single-device
    protocol.

The implementation is exactly the ``Engine`` placement hooks: this class
overrides ``_place_params`` / ``_place_kv`` (NamedShardings instead of a
device), pins the decode and prefill programs' output shardings so the
carried state's placement can never drift between calls (drift = a
silent retrace, which the ``decode_traces == 1`` contract would catch as
a correctness failure), and supplies the two constraint hooks that make
the math BYTE-IDENTICAL to the single-device engine rather than merely
close: ``_decode_out_sync`` re-replicates the per-head attention output
before the out projection, and ``_logits_sync`` re-replicates the
vocab-sharded logits before sampling. With those pinned, no contracted
dimension is ever sharded — every collective GSPMD inserts is an
all-gather / gather (pure data movement), never a psum (float
reassociation) — so token equality holds by construction, the same way
paged-vs-dense equality does (tests/test_mesh_engine.py pins it).

Because the surface is identical, everything above composes unchanged:
``ReplicaSet`` supervision treats a mesh engine exactly like a
single-chip one (a replica becomes a mesh SLICE — the engine factory
hands replica i devices ``[i*m, (i+1)*m)``, ``serve_specs
.slice_devices``), process isolation spawns a worker that builds its
MeshEngine from its own jax client's device slice, and socket transport
/ failover / deterministic replay carry over with zero changes to
``replica.py``'s supervision logic.

``paged_attn='kernel'`` is gated with the typed ``MeshPagedAttnError``:
the Pallas kernel is a custom call GSPMD cannot partition — riding the
per-shard pool slices needs a shard_map wrapper around the kernel entry,
the documented follow-up (docs/SERVING.md 'Mesh-sharded engine'). The
gather oracle rides the sharded pool today.
"""

from __future__ import annotations

from typing import Optional, Sequence

from dalle_pytorch_tpu.serve.engine import Engine
from dalle_pytorch_tpu.utils.metrics import structured_event


class MeshPagedAttnError(ValueError):
    """Typed rejection of ``paged_attn='kernel'`` on a mesh engine: the
    Pallas ragged paged-attention kernel is a custom call the GSPMD
    partitioner cannot split across shards — serving it on a mesh needs
    the shard_map wrapper (per-shard head slices of the pool), which is
    the documented follow-up. Raised HERE, at engine init, instead of an
    opaque partitioner failure inside the first fused chunk."""

    def __init__(self, record: dict):
        super().__init__(
            "paged_attn='kernel' is not yet supported on a mesh engine: "
            "the Pallas kernel is an opaque custom call GSPMD cannot "
            "partition across the KV pool's head shards. Use "
            "paged_attn='gather' (the parity oracle rides the sharded "
            "pool), or serve single-device replicas for the kernel path "
            "(docs/SERVING.md 'Mesh-sharded engine').")
        self.record = record


class MeshEngine(Engine):
    """``Engine`` over a device mesh. ``devices`` picks the slice (all
    visible devices when None); every other argument, counter, and
    method is the base engine's — the class is placement + program-
    sharding only, which is the entire point (see module docstring)."""

    def __init__(self, params: dict, cfg, queue, *,
                 devices: Optional[Sequence] = None,
                 **kwargs):
        import jax

        from dalle_pytorch_tpu.parallel import serve_specs as SS

        if kwargs.get("paged_attn", "gather") == "kernel":
            raise MeshPagedAttnError(structured_event(
                "serve_mesh_paged_attn_unsupported",
                paged_attn="kernel"))
        self.devices = tuple(devices) if devices is not None \
            else tuple(jax.devices())
        self.mesh = SS.serve_mesh(self.devices)
        self.n_shards = len(self.devices)
        self._rep = SS.replicated(self.mesh)
        self._sync = SS.replicate_sync(self.mesh)
        self._kv_shardings: Optional[dict] = None
        self.kv_sharded = False
        self.params_sharded = False
        # the base engine's ``device`` IS the placement every host-side
        # put flows through — handing it the replicated NamedSharding
        # makes admission tensors, block tables, kill masks, and the
        # per-slot state land replicated across the slice with zero
        # changes to the base code paths
        super().__init__(params, cfg, queue, device=self._rep, **kwargs)

    # -- placement hooks ----------------------------------------------------

    def _place_params(self, params):
        import jax

        from dalle_pytorch_tpu.parallel import serve_specs as SS
        from jax.sharding import PartitionSpec as P
        specs = SS.serve_param_specs(params, self.cfg, self.mesh)
        self.params_sharded = any(
            s.spec != P() for s in jax.tree_util.tree_leaves(specs))
        return jax.tree.map(jax.device_put, params, specs)

    def _place_kv(self, cache: dict) -> dict:
        import jax

        from dalle_pytorch_tpu.parallel import serve_specs as SS
        self._kv_shardings = SS.serve_kv_specs(cache, self.mesh)
        self.kv_sharded = SS.kv_is_sharded(self._kv_shardings)
        return {k: jax.device_put(v, self._kv_shardings[k])
                for k, v in cache.items()}

    def _jit_decode(self, impl, donate):
        import jax
        # output shardings PINNED, not propagated: the decode outputs
        # are rebound as the next chunk's inputs, so a propagation
        # choice that drifted from the input NamedShardings would force
        # a retrace on the second call — the one-compile contract
        # (decode_traces == 1) turns that drift into a test failure
        # rather than a silent 2x compile. Order: (cur_tok, pos, active,
        # cache, emit_ring).
        rep = self._rep
        return jax.jit(impl, donate_argnums=donate,
                       out_shardings=(rep, rep, rep,
                                      dict(self._kv_shardings), rep))

    def _jit_prefill_program(self, pre):
        import jax
        # (cache, cur_tok, pos, active, rng, temp, topk_k, top_p,
        # h_last) — same drift-proofing as the decode program, once per
        # bucket; h_last (the prefix cache's insert payload) replicates
        # so a warm hit's first-token sample runs on whole rows
        rep = self._rep
        return jax.jit(pre, out_shardings=(
            dict(self._kv_shardings), rep, rep, rep, rep, rep, rep, rep,
            rep))

    def _jit_warm_program(self, warm):
        import jax
        # (cur_tok, pos, active, rng, temp, topk_k, top_p) — the warm
        # admission touches only replicated per-slot state
        rep = self._rep
        return jax.jit(warm, out_shardings=(rep,) * 7)

    def _jit_pool_update(self, fn):
        import jax
        # the COW boundary-page fork returns the UPDATED pool: pin its
        # shardings, or a propagation choice could drift the KV store's
        # placement and silently retrace the fused decode program
        return jax.jit(fn, out_shardings=dict(self._kv_shardings))

    # -- the byte-identity constraints --------------------------------------

    def _logits_sync(self, logits):
        # the logits head is vocab-sharded (column-parallel: every
        # element computed whole on one shard) — gather it back before
        # the sampler, whose softmax/cumsum reductions must never run
        # over a sharded axis (reassociation breaks byte-identity)
        return self._sync(logits)

    def _decode_out_sync(self):
        # ops.decode applies this to the per-head attention output
        # BEFORE the out projection: gathered heads (data movement)
        # instead of a partial-summed projection (reassociation)
        return self._sync

    # -- observability ------------------------------------------------------

    def _mesh_stats(self) -> dict:
        from dalle_pytorch_tpu.parallel import serve_specs as SS
        return {
            "devices_per_replica": self.n_shards,
            "mesh_shape": SS.mesh_shape_desc(self.mesh),
            "mesh_devices": SS.mesh_device_ids(self.mesh),
            "kv_sharded": self.kv_sharded,
            "params_sharded": self.params_sharded,
            # where the pool actually LIVES: resident bytes per shard
            # (== global/m only when the heads axis divided)
            "kv_hbm_bytes_per_shard": SS.per_shard_bytes(self.cache),
            "param_bytes_per_shard": SS.per_shard_bytes(self.params),
        }


def hbm_report(engine: Engine) -> dict:
    """Modeled HBM residency of an engine's two dominant terms — params
    and the KV store — global and per shard. Works on a plain ``Engine``
    (per-shard == global: one chip holds everything) and a ``MeshEngine``
    (per-shard is what one device of the slice actually stores). This is
    the number ``bench_serve``'s ``mesh_compare`` HBM-budget leg asserts
    against a device budget, and what operators read next to
    ``mesh_shape`` in /stats."""
    from dalle_pytorch_tpu.parallel import serve_specs as SS
    params_b = SS.param_bytes(engine.params)
    kv_b = engine.kv_hbm_bytes()
    params_ps = SS.per_shard_bytes(engine.params)
    kv_ps = SS.per_shard_bytes(engine.cache)
    return {
        "param_bytes": params_b,
        "kv_hbm_bytes": kv_b,
        "total_bytes": params_b + kv_b,
        "param_bytes_per_shard": params_ps,
        "kv_hbm_bytes_per_shard": kv_ps,
        "total_bytes_per_shard": params_ps + kv_ps,
        "devices": getattr(engine, "n_shards", 1),
    }
