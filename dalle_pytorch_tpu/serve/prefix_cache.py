"""Cross-request prefix cache: content-addressed, refcounted prompt KV.

Production text→image traffic is prefix-heavy — shared style/system
prompts, retry storms of the same prompt, N-samples-per-prompt fan-out —
yet a refcount-blind paged engine re-runs full prefill and allocates a
private copy of the prompt's KV pages on every admission. This module is
the host-side index that turns the page pool (``serve/kv_pool.py``) into
a shared prompt store:

  * a prefix ENTRY is keyed by ``prefix_key`` — (model version, prompt
    token hash, layer-set signature, cache dtype) — and owns, via
    ``PageAllocator.retain``:
      - the prompt's FULL pages (every page wholly below the prompt
        length ``t0``): these are read-only by construction, because
        decode only ever appends at positions >= t0, which land in
        later pages — a warm hit maps them straight into the new slot's
        block table (refcount++, zero prefill FLOPs, zero new pages);
      - a device-side SNAPSHOT of the partial boundary page (when
        ``t0 % page_size != 0``): the copy-on-write source — a warm hit
        allocates one private page and forks the snapshot into it, so
        the consumer's decode writes diverge without touching the
        cached copy (``kv_pool.restore_page``);
      - the prompt's last hidden row ``h_last`` (dim,): what the first
        sampled token is computed from — the warm-admission program is
        one ``to_logits`` + per-slot sample over cached rows, byte-
        identical to the cold prefill's first token because prefill
        rows are batch-row-independent and deterministic.
  * entries are LRU: the index holds a bounded number, and the engine
    ``shrink``s it under page pressure BEFORE evicting a live request —
    cached prefixes are a perf lever, live requests are work.

Keying includes the exact token tuple as a collision check (the hash
addresses, the tokens verify), the engine's model version (weight
hot-swap must not serve stale KV), and the layer-set signature (depth /
heads / sparse pattern — a different stack shape stores different rows).

Module-level imports stay jax-free (the ``serve`` package's lazy-import
discipline); entry payloads hold device arrays the ENGINE created.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple


def layer_signature(cfg) -> Tuple:
    """The layer-set half of the prefix key: everything about the stack
    that decides WHAT a cached prompt row contains. Two engines sharing
    a pool layout but differing in any of these must never share KV."""
    return (int(cfg.depth), int(cfg.heads), int(cfg.dim_head),
            bool(cfg.reversible), tuple(bool(s) for s in
                                        cfg.sparse_pattern))


def prefix_key(codes: Sequence[int], *, model_version: str,
               layer_sig: Tuple, quantized: bool) -> str:
    """Content address of one prompt's KV: sha256 over (model version,
    layer-set signature, cache dtype class, the exact token ids)."""
    h = hashlib.sha256()
    h.update(repr((str(model_version), layer_sig,
                   bool(quantized))).encode())
    h.update(b"|")
    h.update(",".join(str(int(c)) for c in codes).encode())
    return h.hexdigest()


def content_key(codes: Sequence[int], *, cfg, model_version: str,
                quantized: bool = False) -> str:
    """The prompt's content address computed FROM the model config —
    the gateway's routing key. This is the SAME key an engine with this
    (cfg, model_version, dtype) computes at admission, which is the
    whole point of prefix-affinity routing: the rendezvous hash over
    this key sends a repeated prompt to the cell whose PrefixIndex
    already holds the entry it names. Accepts either the transformer
    config or a DALLEConfig wrapping one (the engine signs
    ``cfg.transformer``)."""
    return prefix_key(codes, model_version=model_version,
                      layer_sig=layer_signature(
                          getattr(cfg, "transformer", cfg)),
                      quantized=quantized)


class PrefixEntry:
    """One cached prompt span. ``full_pages`` are the physical ids of
    the pages wholly below ``t0`` (the index holds one reference on
    each); ``boundary_snap`` is the device snapshot of the partial
    boundary page (None when ``t0 % page_size == 0``); ``h_last`` is
    the (dim,) hidden row the first token samples from."""

    __slots__ = ("key", "codes", "t0", "full_pages", "boundary_snap",
                 "h_last", "hits")

    def __init__(self, key: str, codes: Tuple[int, ...], t0: int,
                 full_pages: List[int], boundary_snap: Optional[dict],
                 h_last):
        self.key = key
        self.codes = tuple(int(c) for c in codes)
        self.t0 = int(t0)
        self.full_pages = list(full_pages)
        self.boundary_snap = boundary_snap
        self.h_last = h_last
        self.hits = 0


class PrefixIndex:
    """LRU map ``prefix_key -> PrefixEntry`` over one engine's page
    pool. The index RETAINS every entry's full pages (the allocator's
    refcounts are what make 'freed only at zero' true when a consumer
    and the cache both map a page), and releases them when an entry is
    evicted — by capacity, by an explicit ``shrink`` under page
    pressure, or by ``clear`` (weight hot-swap)."""

    def __init__(self, alloc, max_entries: int = 256):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got "
                             f"{max_entries}")
        self.alloc = alloc
        self.max_entries = int(max_entries)
        self._entries: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.inserted = 0
        self.evicted = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    @property
    def pages_held(self) -> int:
        """References the index currently holds (full pages across all
        entries) — NOT extra HBM: shared pages are physical once."""
        return sum(len(e.full_pages) for e in self._entries.values())

    def lookup(self, key: str,
               codes: Sequence[int]) -> Optional[PrefixEntry]:
        """The warm-hit probe. The hash addresses, the stored tokens
        VERIFY — a colliding key must read as a miss, never as another
        prompt's KV."""
        e = self._entries.get(key)
        if e is None or e.codes != tuple(int(c) for c in codes):
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        e.hits += 1
        self.hits += 1
        return e

    def insert(self, entry: PrefixEntry) -> None:
        """Index a freshly prefilled prompt span: retain its full pages
        (the cache's own reference) and make it MRU. Inserting over an
        existing key replaces the old entry (releases its holds)."""
        if entry.key in self._entries:
            self._evict(entry.key)
        self.alloc.retain(entry.full_pages)
        self._entries[entry.key] = entry
        self._entries.move_to_end(entry.key)
        self.inserted += 1
        while len(self._entries) > self.max_entries:
            self._evict(next(iter(self._entries)))

    def _evict(self, key: str) -> None:
        e = self._entries.pop(key)
        self.alloc.release(e.full_pages)
        self.evicted += 1

    def shrink(self, pages_needed: int) -> int:
        """Release LRU entries until the allocator's free list could
        satisfy ``pages_needed`` (or the index is empty) — the engine
        calls this BEFORE evicting a live request. Returns entries
        dropped. Releasing an entry frees only pages no live slot
        still maps (refcounts), so this can under-deliver: the caller
        re-checks ``alloc.free`` and falls back to request eviction."""
        dropped = 0
        while self._entries and self.alloc.free < pages_needed:
            self._evict(next(iter(self._entries)))
            dropped += 1
        return dropped

    def clear(self) -> int:
        """Drop every entry (weight hot-swap / engine teardown)."""
        n = len(self._entries)
        for key in list(self._entries):
            self._evict(key)
        return n

    def stats(self) -> dict:
        return {
            "prefix_entries": len(self._entries),
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_inserted": self.inserted,
            "prefix_evicted": self.evicted,
            "prefix_pages_held": self.pages_held,
        }
