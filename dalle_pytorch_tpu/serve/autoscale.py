"""Load-driven autoscaler for the elastic replica set.

The scale API (``ReplicaSet.add_replica`` / ``remove_replica``) is the
mechanism; this module is the POLICY: a small control loop watching the
same signals /stats exports — slot occupancy, shared-queue depth, and
paged-KV page pressure — and calling the same two operator calls an
admin would, capped by ``min_replicas``/``max_replicas``. Nothing here
touches routing, engines, or requests: the autoscaler is a client of
the operator surface, so everything it does is reproducible by hand
(and auditable — EVERY decision that changes, or tries to change, the
fleet is a structured ``autoscale_decision`` event).

Control-loop discipline, each clause load-bearing:

  * **Hysteresis**: a breach must persist for ``breach_ticks``
    consecutive ticks before the scaler acts. One burst wave or one
    harvest stall must not add a replica (bring-up costs a compile);
    one idle tick must not remove one (the next wave would pay the
    bring-up again). The out- and in-breach counters reset each other:
    an oscillating signal keeps the fleet exactly where it is.
  * **Cooldown**: after any action, ``cooldown_s`` of silence. A fresh
    replica takes seconds to compile and drain the backlog; deciding
    again off the still-congested signals would ladder straight to
    ``max_replicas`` on every burst.
  * **Caps are typed, not clamped silently**: at ``max_replicas`` the
    scaler emits an ``at_max`` decision (the operator sees saturation
    in the event stream — that is a capacity-planning signal, not
    noise); at ``min_replicas`` scale-in simply never triggers.
  * **A reshaping fleet is left alone**: while a rolling upgrade owns
    the set (``ReplicaSet.rolling_upgrade``), or while a prior
    decision's replica is still coming up, the scaler holds — two
    owners reshaping one fleet is how half-configured states happen
    (the scale API would reject it typed anyway; the policy simply
    never asks).

Drivable two ways, mirroring the set itself: ``tick(now)`` from a sync
driver (tests, bench — deterministic), or ``start()`` for a background
thread at ``interval_s`` (what ``serve_dalle --autoscale`` runs).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from dalle_pytorch_tpu.utils.metrics import structured_event


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The policy knobs (``serve_dalle --autoscale_*``). Scale OUT when
    occupancy exceeds ``high_occupancy``, the shared queue backs up
    past ``queue_high`` entries per live replica, or any replica's free
    pages fall below ``page_low_frac`` of its pool — sustained for
    ``breach_ticks`` ticks. Scale IN when occupancy sits below
    ``low_occupancy`` with an empty queue for the same stretch."""

    min_replicas: int = 1
    max_replicas: int = 4
    high_occupancy: float = 0.85
    low_occupancy: float = 0.25
    queue_high: int = 4              # shared-queue entries per replica
    page_low_frac: float = 0.10      # pages_free/num_pages pressure line
    breach_ticks: int = 3            # hysteresis: consecutive breaches
    cooldown_s: float = 10.0         # silence after any action
    interval_s: float = 1.0          # threaded tick cadence

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if not 0.0 <= self.low_occupancy < self.high_occupancy <= 1.0:
            raise ValueError(
                f"need 0 <= low_occupancy < high_occupancy <= 1, got "
                f"{self.low_occupancy}/{self.high_occupancy}")
        if self.breach_ticks < 1:
            raise ValueError(f"breach_ticks must be >= 1, got "
                             f"{self.breach_ticks}")


class Autoscaler:
    """The policy loop over one ``ReplicaSet``. ``tick()`` reads the
    signals, updates the hysteresis counters, and — past the breach
    and cooldown gates — calls the scale API; every fleet-changing
    decision (and every typed rejection) is a structured
    ``autoscale_decision`` event and is returned to the caller."""

    def __init__(self, replica_set, policy: AutoscalePolicy,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        from dalle_pytorch_tpu.serve.replica import ReplicaSet
        if not isinstance(replica_set, ReplicaSet):
            raise TypeError(
                "Autoscaler needs a ReplicaSet — a single engine has "
                "no slots to add (serve with replicas >= 1 through "
                "the replica set, or drop --autoscale)")
        self.rs = replica_set
        self.policy = policy
        # default to the SET's RecordingMetrics: every decision then
        # lands in the set-level flight ring (always on) even when no
        # JSONL sink was configured — "why did the fleet reshape" must
        # be answerable from /debug/events alone
        self.metrics = metrics if metrics is not None \
            else getattr(replica_set, "metrics", None)
        self.clock = clock
        # per-replica pool size for the page-pressure signal. A child-
        # process engine lives in another interpreter, and num_pages=0
        # (the default) means "fully provisioned" — resolved engine-
        # side — so model it here with the engine's own formula, or the
        # signal would silently read 1.0 forever on exactly the fleets
        # that need it.
        self._modeled_pages = 0
        if replica_set.kv == "paged":
            from dalle_pytorch_tpu.serve import kv_pool as KV
            kw = replica_set._engine_kwargs
            page_size = int(kw.get("page_size") or 0) \
                or min(16, replica_set.cfg.seq_len)
            self._modeled_pages = int(kw.get("num_pages") or 0) or (
                int(kw.get("num_slots", 4))
                * KV.pages_for(replica_set.cfg.seq_len, page_size) + 1)
        self.out_breach = 0          # consecutive scale-out breaches
        self.in_breach = 0           # consecutive scale-in breaches
        self.last_action_t: Optional[float] = None
        self.decisions: list = []    # every acted/rejected decision
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals ------------------------------------------------------------

    def signals(self) -> dict:
        """One reading of the load signals, straight off the set's own
        host-side bookkeeping (no device syncs): live replica count,
        mean slot occupancy, shared-queue depth, and the worst
        replica's free-page fraction (1.0 when not paged / unknown)."""
        from dalle_pytorch_tpu.serve.replica import RUNNING
        rs = self.rs
        live = [r for r in rs.replicas
                if r.state == RUNNING and r.engine is not None
                and not r.canary]
        slots = sum(r.engine.num_slots for r in live)
        active = sum(r.engine.active_slots() for r in live)
        page_frac = 1.0
        if rs.kv == "paged":
            for r in live:
                e = r.engine
                free = e.pages_free if rs.isolation == "process" \
                    else e.alloc.free
                total = getattr(e, "num_pages", 0) \
                    or self._modeled_pages
                if free is not None and free >= 0 and total:
                    page_frac = min(page_frac, free / total)
        return {
            "live_replicas": len(live),
            "occupancy": active / slots if slots else 1.0,
            "queue_depth": rs.queue.depth(),
            "page_free_frac": round(page_frac, 4),
        }

    # -- the decision -------------------------------------------------------

    def _decide(self, sig: dict) -> Optional[str]:
        """Pure policy: signals -> 'out' | 'in' | None, updating the
        hysteresis counters. Separated from ``tick`` so tests can
        table-drive it."""
        p = self.policy
        live = max(sig["live_replicas"], 1)
        hot = (sig["occupancy"] > p.high_occupancy
               or sig["queue_depth"] > p.queue_high * live
               or sig["page_free_frac"] < p.page_low_frac)
        cold = (sig["occupancy"] < p.low_occupancy
                and sig["queue_depth"] == 0)
        self.out_breach = self.out_breach + 1 if hot else 0
        self.in_breach = self.in_breach + 1 if cold else 0
        if self.out_breach >= p.breach_ticks:
            return "out"
        if self.in_breach >= p.breach_ticks:
            return "in"
        return None

    def _record(self, action: str, sig: dict, **fields) -> dict:
        rec = structured_event("autoscale_decision", action=action,
                               **sig, **fields)
        self.decisions.append(rec)
        if self.metrics is not None:
            try:
                self.metrics.event(**rec)
            except Exception:   # noqa: BLE001 — observability only
                pass
        return rec

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One control iteration. Returns the decision record when the
        tick acted (or was typed-rejected at a cap), None on a quiet
        tick — so a sync driver can count decisions directly."""
        from dalle_pytorch_tpu.serve import replica as R
        p = self.policy
        now = self.clock() if now is None else now
        rs = self.rs
        if rs._upgrading:
            # a rolling upgrade owns the fleet; reshaping under it
            # would be typed-rejected anyway — don't even ask, and
            # don't let the upgrade's drain spikes charge the counters
            self.out_breach = self.in_breach = 0
            return None
        if self.last_action_t is not None \
                and now - self.last_action_t < p.cooldown_s:
            return None
        # a replica still coming up (spawned, compiling, circuit-broken
        # from a previous decision) is capacity in flight: deciding
        # again off the same congestion would double-spend
        if any(r.state == R.BROKEN or (r.state == R.RUNNING
                                       and not rs._replica_serving(r))
               for r in rs.replicas if r.state != R.RETIRED):
            return None
        sig = self.signals()
        action = self._decide(sig)
        if action is None:
            return None
        live = sig["live_replicas"]
        if action == "out":
            self.out_breach = 0
            if live >= p.max_replicas:
                self.last_action_t = now    # don't re-emit every tick
                return self._record("at_max", sig,
                                    max_replicas=p.max_replicas)
            try:
                index = rs.add_replica()
            except R.ScaleError as e:
                self.last_action_t = now
                return self._record("rejected", sig,
                                    error=e.record.get("reason"))
            self.last_action_t = now
            return self._record("scale_out", sig, replica=index,
                                replicas=rs.n_replicas)
        self.in_breach = 0
        if live <= p.min_replicas:
            return None         # quietly at floor: idle is not an event
        # retire the youngest live replica: the one the last burst
        # added, whose retirement disturbs the least-warmed caches
        victim = max((r for r in rs.replicas
                      if r.state == R.RUNNING and not r.canary),
                     key=lambda r: r.index, default=None)
        if victim is None:
            return None
        # remove_replica(drain=True) live-migrates the victim's
        # in-flight work to survivors before the fence — the delta of
        # the set's migrated_tokens_saved counter across the call is
        # what this decision avoided re-decoding
        saved0 = rs.migrated_tokens_saved
        try:
            reclaimed = rs.remove_replica(victim.index, drain=True,
                                          reason="autoscale scale-in")
        except R.ScaleError as e:
            self.last_action_t = now
            return self._record("rejected", sig,
                                error=e.record.get("reason"))
        self.last_action_t = now
        return self._record("scale_in", sig, replica=victim.index,
                            reclaimed=reclaimed,
                            tokens_saved=rs.migrated_tokens_saved
                            - saved0,
                            replicas=rs.n_replicas)

    # -- threaded drive -----------------------------------------------------

    def start(self) -> "Autoscaler":
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-autoscaler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the policy loop must
                pass            # never take down serving
            self._stop.wait(self.policy.interval_s)

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
