"""Multi-cell gateway: one front door over a fleet of ReplicaSets.

One ``InferenceServer`` — even a replica set — is one host's worth of
engines. The gateway is the fleet-of-fleets tier above it: N
independent CELLS (each an ``InferenceServer``, typically fronting a
``ReplicaSet``) behind a single submit/HTTP surface, with the three
front-door jobs production serving actually needs:

  * PREFIX-AFFINITY ROUTING. The rendezvous (highest-random-weight)
    hash key is the prompt's content-addressed prefix key
    (serve/prefix_cache.py ``content_key`` — model version, layer
    signature, cache dtype, exact tokens): the SAME key every cell's
    engine uses for its PrefixIndex. A repeated prompt therefore lands
    on the cell whose index is already warm — zero prefill FLOPs on the
    hit — and cross-request KV reuse pays off fleet-wide instead of
    per-cell by luck. When the affine cell is saturated the request
    SPILLS to the cell with the most free slots, as a typed
    ``gateway_spill`` event (affinity traded for latency, observable).

  * TENANCY AT ADMISSION (serve/tenancy.py). API keys verified
    constant-time, token-bucket rate limits, fleet-wide page budgets —
    all charged BEFORE the shared queue sees the request, so one
    abusive tenant exhausts only its own quota (typed 429 with
    retry-after) while everyone else's latency holds. Under
    saturation the shared queue drains by weighted-fair virtual finish
    time (scheduler.WeightedFairQueue), so throughput shares follow
    configured weights, not arrival aggression.

  * SLO TIERS + HEDGED SENDS. A request un-fulfilled past its tier's
    hedge threshold is speculatively duplicated onto the next-ranked
    alive cell. First fulfil wins — ``RequestHandle.fulfill`` is
    already first-write-wins — and the loser is cooperatively
    cancelled (its cell handle fulfilled ``cancelled``; the engine's
    harvest skips done handles, discarding the dead tokens and freeing
    the slot at the natural completion point).

Cell death is a first-class event, not an outage: a whole cell dying
mid-stream (``faults.gateway_cell_down_at_request`` drives it
deterministically) fences the cell and REQUEUES every flight it held —
original ``queue_seq`` and virtual-time tags preserved — for replay on
a surviving cell, byte-identical per weights_version, zero loss.

Module-level imports are jax-free (the serve package's discipline):
the gateway never touches a device — cells do.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from dalle_pytorch_tpu.obs import registry as oreg
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.serve import auth
from dalle_pytorch_tpu.serve import prefix_cache as PC
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import tenancy as T
from dalle_pytorch_tpu.utils.metrics import structured_event


class Cell:
    """One ReplicaSet-backed ``InferenceServer`` behind the gateway.
    The gateway tracks its own in-flight count per cell as the load
    signal — cheap, lock-local, and exactly the quantity the spill
    decision needs (stats() walks the whole set)."""

    def __init__(self, name: str, server, index: int):
        self.name = str(name)
        self.server = server
        self.index = int(index)
        self.inflight = 0          # gateway-tracked flights on this cell
        self.routed = 0            # lifetime dispatches (hedges included)
        self.killed = False
        try:
            self.capacity = max(int(server.stats().get("num_slots", 1)),
                                1)
        except Exception:   # noqa: BLE001 — a cell that cannot answer
            # stats at attach time still joins with minimal capacity
            self.capacity = 1

    def alive(self) -> bool:
        return not self.killed and self.server.engine_alive()


@dataclasses.dataclass
class _Flight:
    """Gateway-side bookkeeping for one admitted request, from tenant
    admission to terminal fulfil. ``pages`` is the tenant-budget
    reservation released exactly once (``released`` guards it)."""
    handle: S.RequestHandle
    tenant: str
    pages: int
    key: str = ""
    rank: List[int] = dataclasses.field(default_factory=list)
    cell: Optional[Cell] = None
    cell_handle: Optional[S.RequestHandle] = None
    hedge_cell: Optional[Cell] = None
    hedge_handle: Optional[S.RequestHandle] = None
    dispatch_t: float = 0.0
    replays: int = 0
    released: bool = False
    # gateway-owned stream sinks (serve/stream.py), built ONCE at
    # admission and handed to every dispatch of this flight — replay on
    # a survivor cell re-feeds the SAME sinks, and the per-sink
    # high-water mark dedupes the replayed prefix, so the client's
    # stream never stutters across a cell death
    sinks: Optional[List] = None


# federation: the cell counters the gateway re-exposes with a ``cell``
# label — the per-cell samples MUST sum to the unlabeled fleet value
# (pinned by test), so an operator can read one scrape for both.
_FEDERATED_COUNTERS = (
    ("requests_submitted", "dalle_serve_requests_submitted_total"),
    ("completed", "dalle_serve_requests_completed_total"),
    ("tokens_decoded", "dalle_serve_tokens_decoded_total"),
    ("prefix_hits", "dalle_serve_prefix_hits_total"),
)

_MAX_REPLAYS = 3          # per flight, before a typed error fulfil
_EVENT_RING = 512         # bounded gateway event history


class Gateway:
    """The fleet front door. ``cells`` are started ``InferenceServer``s
    (the gateway does not start them; ``close(close_cells=True)``
    closes them). ``tenants`` is a ``tenancy.TenantTable`` or None (the
    anonymous single-tenant gateway — no auth, no quotas, weight 1).

    ``cfg``/``model_version``/``quantized`` must describe the cells'
    engines: they parameterize the routing key so it matches what each
    cell's PrefixIndex computes at admission. ``affinity=False``
    degrades routing to hash-blind least-loaded — the control arm of
    the bench's affinity comparison, and an escape hatch."""

    def __init__(self, cells: Sequence, *, tenants=None, cfg=None,
                 model_version: str = "v0", quantized: bool = False,
                 affinity: bool = True, queue_depth: int = 256,
                 max_prompt_len: Optional[int] = None,
                 pages_per_request: int = 1,
                 admin_token: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tick_s: float = 0.005,
                 hedge_check_s: float = 0.05,
                 on_event=None):
        if not cells:
            raise ValueError("a gateway needs at least one cell")
        self.cells = [c if isinstance(c, Cell) else Cell(f"cell{i}", c, i)
                      for i, c in enumerate(cells)]
        self.tenants: Optional[T.TenantTable] = tenants
        self.cfg = cfg
        self.model_version = str(model_version)
        self.quantized = bool(quantized)
        self.affinity = bool(affinity)
        self.pages_per_request = max(int(pages_per_request), 0)
        self.clock = clock
        self.tick_s = float(tick_s)
        self.hedge_check_s = float(hedge_check_s)
        self.on_event = on_event
        if admin_token is None:
            import secrets
            admin_token = secrets.token_hex(16)
        self.admin_token = admin_token
        # per-request decode cost for the image-token bucket: the
        # model's image span (every completion decodes exactly this
        # many tokens), or 0 (cost-free) without a cfg
        self.image_tokens = int(cfg.image_seq_len) if cfg is not None \
            else 0
        weight_of = tenants.weight_of if tenants is not None \
            else (lambda name: 1.0)
        # WFQ cost is measured in IMAGE TOKENS, not requests: a
        # completion decodes its image span per sample, so the charge
        # is n_samples x (override or full image_seq_len) — a fan-out
        # tenant pays for N samples' decoded work up front, and a
        # variable-resolution tenant can't multiply its share by
        # splitting work across more, smaller requests (the short grid
        # costs exactly its shorter span). Speculation doesn't change
        # the charge: rejected drafts are never delivered, so the true
        # per-sample token cost is the span at every acceptance rate.
        # Without a cfg there is no token count to meter — fall back to
        # n_samples per request (uniform per-sample cost keeps WFQ
        # exact, just sample-denominated).
        def _wfq_cost(request: S.Request) -> float:
            n = max(int(request.n_samples), 1)
            if not self.image_tokens:
                return float(n)
            span = int(request.image_seq_len_override) \
                or self.image_tokens
            return float(n * span)
        self.queue = S.WeightedFairQueue(
            max_depth=queue_depth, max_prompt_len=max_prompt_len,
            clock=clock, on_event=self._event_sink,
            weight_of=weight_of, cost_fn=_wfq_cost)
        self._lock = threading.Lock()
        self._flights: Dict[int, _Flight] = {}
        self._events: "collections.deque" = collections.deque(
            maxlen=_EVENT_RING)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (lifetime-monotonic; /metrics re-exposes them)
        self.routed = 0
        self.spills = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.replays = 0
        self.cell_downs = 0
        self.completed = 0
        self.expired = 0
        self.hedge_stream_rejects = 0
        # per-tenant e2e latency (submit -> terminal fulfil), the
        # histogram the degradation contract's p95 is read from
        self.registry = oreg.Registry()
        self.hist_e2e = self.registry.histogram(
            "dalle_gateway_e2e_latency_seconds",
            "Gateway end-to-end request latency by tenant")

    # -- events --------------------------------------------------------

    def _event_sink(self, record: dict) -> None:
        self._events.append(record)
        if self.on_event is not None:
            self.on_event(record)

    def _event(self, kind: str, **fields) -> dict:
        record = structured_event(kind, **fields)
        self._event_sink(record)
        return record

    def events(self, kind: Optional[str] = None) -> List[dict]:
        out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Gateway":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._pump, name="gateway-pump", daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 30.0,
              close_cells: bool = True) -> None:
        self.queue.close()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for h in self.queue.drain():
            h.fulfill(S.Result(
                status=S.CANCELLED, request_id=h.request.request_id,
                reason="gateway shutdown"))
            self._finish(h.request.request_id, completed=False)
        with self._lock:
            flights = list(self._flights.values())
        for fl in flights:
            fl.handle.fulfill(S.Result(
                status=S.CANCELLED,
                request_id=fl.handle.request.request_id,
                reason="gateway shutdown"))
            self._finish(fl.handle.request.request_id, completed=False)
        if close_cells:
            for cell in self.cells:
                if not cell.killed:
                    cell.server.close(timeout)

    # -- admission -----------------------------------------------------

    def _sample_span(self, override: int) -> int:
        """Per-sample decoded image span: the override grid when the
        request carries one, else the model's full span."""
        return int(override) or self.image_tokens

    def _flight_pages(self, n_samples: int, override: int) -> int:
        """The tenant page charge for one flight, COW-aware: a best-of-
        N group shares its prompt span across all N members (PR 13's
        refcounted prefix pages), so the true footprint is ONE prompt
        span plus N generation spans — not N full requests. Scaled in
        ``pages_per_request`` units off the model's token geometry; a
        short-grid override shrinks the per-sample generation share
        proportionally. Without a cfg the geometry is unknown — charge
        the conservative N x pages_per_request."""
        base = self.pages_per_request
        n = max(int(n_samples), 1)
        if base == 0:
            return 0
        if n == 1 and not override:
            return base
        if self.cfg is None:
            return base * n
        text = int(self.cfg.text_seq_len)
        gen = self._sample_span(override)
        full = text + int(self.cfg.image_seq_len)
        cow = text + n * gen
        return max(int(round(base * cow / full)), 1)

    def submit(self, codes, *, api_key: str = "", seed: int = 0,
               temperature: float = 1.0, filter_thres: float = 0.5,
               top_p: float = 0.0, priority: int = 0,
               deadline_s: Optional[float] = None,
               cfg_scale: float = 0.0,
               stream: bool = False,
               n_samples: int = 1,
               image_seq_len_override: int = 0) -> S.RequestHandle:
        """The fleet submit: authenticate -> charge tenant quotas ->
        enter the weighted-fair queue. Raises the typed ladder:
        ``tenancy.AuthError`` (401), ``tenancy.TenantThrottled`` (429
        with retry-after), ``scheduler.QueueFull`` / ``InvalidRequest``
        / ``QueueClosed`` — every refusal structured, nothing silent.
        The returned handle is the caller's future; the pump thread
        routes, hedges, and replays behind it. ``stream``/``n_samples``
        /``image_seq_len_override`` ride through to the cell: the
        tenant is charged n_samples x the per-sample span up front
        (decoded-work metering), and the page reservation charges the
        COW footprint, not N cold prefills."""
        n_samples = max(int(n_samples), 1)
        override = int(image_seq_len_override)
        tenant = ""
        pages = 0
        if self.tenants is not None:
            spec = self.tenants.authenticate(api_key)
            tenant = spec.name
            pages = self._flight_pages(n_samples, override)
            self.tenants.admit(
                tenant,
                image_tokens=n_samples * self._sample_span(override),
                pages=pages)
        sinks = None
        if stream:
            from dalle_pytorch_tpu.serve.stream import TokenSink
            sinks = (TokenSink.group(n_samples) if n_samples > 1
                     else [TokenSink()])
            for s in sinks:
                # cell-side failover cancels must not end the client's
                # stream — the replayed dispatch re-feeds these sinks;
                # _finish force-closes them at the flight's terminal
                s.replayable = True
        try:
            handle = self.queue.submit(S.Request(
                codes=tuple(int(c) for c in codes), seed=int(seed),
                sampling=S.SamplingParams(
                    temperature=float(temperature),
                    filter_thres=float(filter_thres),
                    top_p=float(top_p)),
                priority=int(priority), deadline_s=deadline_s,
                cfg_scale=float(cfg_scale), tenant=tenant,
                stream=bool(stream), n_samples=n_samples,
                image_seq_len_override=override),
                sink=sinks[0] if sinks else None)
        except S.ServeRejected:
            if self.tenants is not None:
                # all-or-nothing admission: a queue refusal refunds
                # the page reservation the tenant charge just took
                self.tenants.release(tenant, pages=pages,
                                     completed=False)
            raise
        with self._lock:
            self._flights[handle.request.request_id] = _Flight(
                handle=handle, tenant=tenant, pages=pages,
                sinks=sinks)
        return handle

    def generate(self, codes, timeout: Optional[float] = None,
                 **kwargs) -> S.Result:
        return self.submit(codes, **kwargs).result(timeout)

    # -- routing -------------------------------------------------------

    def _rank(self, key: str) -> List[int]:
        """Rendezvous (HRW) order of ALL cells for one routing key:
        stable under cell death (survivor order unchanged — the
        property that makes affinity survive a fence) and uniform
        across keys. Returns cell indices, best first."""
        def score(cell: Cell) -> int:
            h = hashlib.sha256(f"{key}|{cell.name}".encode())
            return int.from_bytes(h.digest()[:8], "big")
        return [c.index for c in
                sorted(self.cells, key=score, reverse=True)]

    def _pick(self, flight: _Flight) -> Optional[Cell]:
        """Choose the target cell for one dispatch. Affinity mode:
        the highest-ranked ALIVE cell, spilling to the most-free cell
        when the affine one is saturated. Hash-blind mode: least
        loaded alive cell (fewest in-flight, then fewest lifetime
        routed, then index). None when nothing alive has a free
        slot."""
        alive = [c for c in self.cells if c.alive()]
        if not alive:
            return None
        free = [c for c in alive if c.inflight < c.capacity]
        if not free:
            return None
        if not self.affinity:
            return min(free, key=lambda c: (c.inflight, c.routed,
                                            c.index))
        by_index = {c.index: c for c in alive}
        affine = next((by_index[i] for i in flight.rank
                       if i in by_index), None)
        if affine is None:
            return min(free, key=lambda c: (c.inflight, c.routed,
                                            c.index))
        if affine.inflight < affine.capacity:
            return affine
        spill = min(free, key=lambda c: (c.inflight, c.routed, c.index))
        self.spills += 1
        self._event("gateway_spill", tenant=flight.tenant,
                    request=flight.handle.request.request_id,
                    affine=affine.name, cell=spill.name,
                    key=flight.key[:12])
        return spill

    def _send(self, flight: _Flight, cell: Cell, now: float
              ) -> Optional[S.RequestHandle]:
        """Submit one flight's request to ``cell``, deadline re-based
        to the remaining budget. A cell-side typed reject returns None
        (the caller requeues — the shared queue, not the cell, owns
        backpressure for gateway traffic)."""
        r = flight.handle.request
        deadline = None
        if r.deadline_t is not None:
            deadline = max(r.deadline_t - now, 0.001)
        try:
            h = cell.server.submit(
                r.codes, seed=r.seed,
                temperature=r.sampling.temperature,
                filter_thres=r.sampling.filter_thres,
                top_p=r.sampling.top_p, priority=r.priority,
                deadline_s=deadline, cfg_scale=r.cfg_scale,
                tenant=r.tenant, stream=r.stream,
                n_samples=r.n_samples,
                image_seq_len_override=r.image_seq_len_override,
                # the gateway's sinks, not fresh cell-side ones: a
                # replay re-feeds the same sinks and the high-water
                # mark dedupes, so the client stream survives the hop
                sinks=flight.sinks)
        except S.InvalidRequest as e:
            # the CELL can never run this request (e.g. streaming into
            # a process-isolated cell): retrying elsewhere in the same
            # fleet shape would spin forever — terminal typed error
            flight.handle.fulfill(S.Result(
                status=S.ERROR,
                request_id=flight.handle.request.request_id,
                reason=str(e.record.get("reason", "invalid_request"))))
            self._finish(flight.handle.request.request_id,
                         completed=False)
            return None
        except S.ServeRejected:
            return None
        cell.inflight += 1
        cell.routed += 1
        return h

    def _dispatch(self, now: float) -> None:
        free = sum(max(c.capacity - c.inflight, 0)
                   for c in self.cells if c.alive())
        ready, dead = self.queue.pop_ready(free, now)
        for h in dead:
            h.fulfill(S.Result(
                status=S.DEADLINE_EXCEEDED,
                request_id=h.request.request_id,
                reason="deadline exceeded in gateway queue"))
            self.expired += 1
            self._finish(h.request.request_id, completed=False)
        for h in ready:
            with self._lock:
                flight = self._flights.get(h.request.request_id)
            if flight is None or h.done():
                continue
            if not flight.rank:
                flight.key = PC.content_key(
                    h.request.codes, cfg=self.cfg,
                    model_version=self.model_version,
                    quantized=self.quantized) if self.cfg is not None \
                    else hashlib.sha256(repr(h.request.codes).encode()
                                        ).hexdigest()
                flight.rank = self._rank(flight.key)
            cell = self._pick(flight)
            if cell is None:
                # nothing alive has a free slot right now: back into
                # the line at the ORIGINAL position (count=False — a
                # capacity wait is a dispatch stall, not backpressure)
                self.queue.requeue(h, count=False)
                continue
            sent = self._send(flight, cell, now)
            if sent is None:
                self.queue.requeue(h, count=False)
                continue
            flight.cell = cell
            flight.cell_handle = sent
            flight.dispatch_t = now
            self.routed += 1
            affine = bool(self.affinity and flight.rank
                          and flight.rank[0] == cell.index)
            self._event("gateway_route",
                        request=h.request.request_id,
                        tenant=h.request.tenant, cell=cell.name,
                        affine=affine, spilled=not affine
                        if self.affinity else False,
                        key=flight.key[:12])
            if faults.on_gateway_dispatch(self.routed):
                self._cell_down(cell)

    # -- failure + completion sweeps ----------------------------------

    def _cell_down(self, cell: Cell) -> None:
        """Fence one cell: mark it dead and close its server. Every
        in-flight request it held completes ``cancelled`` from the
        cell's own shutdown path; the completion sweep turns each into
        a requeue + replay on a survivor."""
        if cell.killed:
            return
        cell.killed = True
        self.cell_downs += 1
        self._event("gateway_cell_down", cell=cell.name,
                    inflight=cell.inflight)
        try:
            cell.server.close(timeout=10.0)
        except Exception as e:   # noqa: BLE001 — a messy corpse must
            # not take the pump thread down with it
            self._event("gateway_cell_close_error", cell=cell.name,
                        error=repr(e))

    def _replay(self, flight: _Flight) -> None:
        """Zero-loss recovery: the flight's cell died (or rejected it)
        — strip its cell-side state and requeue the ORIGINAL handle.
        queue_seq and the WFQ virtual tags are cached on the handle,
        so the replay re-enters at the exact place in line the
        request always owned; decode on the survivor is byte-identical
        per weights_version (the engines' replay contract)."""
        flight.replays += 1
        self.replays += 1
        flight.cell = None
        flight.cell_handle = None
        flight.hedge_cell = None
        flight.hedge_handle = None
        if flight.replays > _MAX_REPLAYS:
            flight.handle.fulfill(S.Result(
                status=S.ERROR,
                request_id=flight.handle.request.request_id,
                reason=f"gateway replay budget exhausted "
                       f"({_MAX_REPLAYS})"))
            self._finish(flight.handle.request.request_id,
                         completed=False)
            return
        self._event("gateway_replay",
                    request=flight.handle.request.request_id,
                    tenant=flight.tenant, attempt=flight.replays)
        self.queue.requeue(flight.handle)

    def _finish(self, request_id: int, completed: bool) -> None:
        """Terminal bookkeeping for one flight, exactly once: release
        the tenant's page reservation, observe e2e latency, drop the
        flight record."""
        with self._lock:
            flight = self._flights.pop(request_id, None)
        if flight is None or flight.released:
            return
        flight.released = True
        if flight.sinks:
            # the flight's terminal IS the stream's terminal: force-
            # close every member sink so the SSE loop ends even when
            # the cell-side arms never got to fulfil (replay budget
            # exhausted, shutdown, disconnect)
            try:
                result = flight.handle.result(timeout=0)
            except TimeoutError:
                result = S.Result(
                    status=S.CANCELLED,
                    request_id=flight.handle.request.request_id,
                    reason="gateway flight terminated")
            for s in flight.sinks:
                try:
                    s.close(result, force=True)
                except Exception:   # noqa: BLE001 — sink teardown must
                    pass            # never block tenant-page release
        if self.tenants is not None and flight.tenant:
            self.tenants.release(flight.tenant, pages=flight.pages,
                                 completed=completed)
        if completed:
            self.completed += 1
        self.hist_e2e.observe(
            max(self.clock() - flight.handle.request.submit_t, 0.0),
            tenant=flight.tenant or "anonymous")

    def _cancel_cell_handle(self, cell: Optional[Cell],
                            handle: Optional[S.RequestHandle],
                            reason: str) -> None:
        """Cooperative cancel of a cell-side handle the gateway no
        longer wants (hedge loser, late duplicate): an external
        first-write-wins fulfil — the cell engine's harvest skips done
        handles, discards the tokens, and frees the slot at its
        natural completion point."""
        if handle is None or cell is None:
            return
        handle.fulfill(S.Result(
            status=S.CANCELLED, request_id=handle.request.request_id,
            reason=reason))
        cell.inflight = max(cell.inflight - 1, 0)

    def _sweep_flights(self, now: float) -> None:
        with self._lock:
            flights = list(self._flights.values())
        for fl in flights:
            if fl.handle.done():        # expired while queued, or the
                # caller went away (SSE disconnect cancel): any live
                # cell-side arm must be cancelled too, so the engine's
                # done-handle reap frees its slots and pages instead
                # of decoding a stream nobody is reading
                for c, h in ((fl.cell, fl.cell_handle),
                             (fl.hedge_cell, fl.hedge_handle)):
                    if h is not None and not h.done():
                        self._cancel_cell_handle(
                            c, h, "gateway flight terminated")
                self._finish(fl.handle.request.request_id,
                             completed=False)
                continue
            if fl.cell_handle is None:
                continue                # still queued for dispatch
            # primary and hedge race; the first arm with a USABLE
            # terminal result wins (first-write-wins at the caller's
            # handle), the loser is cooperatively cancelled
            arms = [(fl.cell, fl.cell_handle),
                    (fl.hedge_cell, fl.hedge_handle)]
            done_arms = [(c, h) for c, h in arms
                         if h is not None and h.done()]
            if not done_arms:
                continue
            for c, _ in done_arms:
                c.inflight = max(c.inflight - 1, 0)
            winner = next(
                ((c, h, h.result(timeout=0)) for c, h in done_arms
                 if h.result(timeout=0).status
                 in (S.OK, S.DEADLINE_EXCEEDED)), None)
            if winner is not None:
                cell, ch, result = winner
                if cell is fl.hedge_cell:
                    self.hedge_wins += 1
                fl.handle.fulfill(dataclasses.replace(
                    result, request_id=fl.handle.request.request_id))
                for oc, oh in arms:
                    if oh is not None and oh is not ch \
                            and not oh.done():
                        self._cancel_cell_handle(oc, oh,
                                                 "hedge loser")
                self._finish(fl.handle.request.request_id,
                             completed=result.status == S.OK)
                continue
            # every done arm died (cell down / cancelled / rejected)
            pending = [(c, h) for c, h in arms
                       if h is not None and not h.done()]
            if pending:
                # one arm is still racing: promote it to primary
                fl.cell, fl.cell_handle = pending[0]
                fl.hedge_cell = fl.hedge_handle = None
            else:
                self._replay(fl)

    def _sweep_dead_cells(self) -> None:
        for cell in self.cells:
            if not cell.killed and not cell.server.engine_alive():
                self._cell_down(cell)

    def _sweep_hedges(self, now: float) -> None:
        if self.tenants is None:
            return
        with self._lock:
            flights = list(self._flights.values())
        for fl in flights:
            if fl.cell_handle is None or fl.hedge_handle is not None \
                    or fl.handle.done():
                continue
            try:
                spec = self.tenants.spec(fl.tenant)
            except KeyError:
                continue
            hedge_after = spec.hedge_after_s
            if hedge_after is None or \
                    now - fl.dispatch_t < hedge_after:
                continue
            if fl.handle.request.stream:
                # two live arms would BOTH feed the client's sinks —
                # interleaved duplicate events, not a latency win. A
                # slow stream keeps its single arm; the refusal is
                # typed so the operator can see hedging declined.
                self.hedge_stream_rejects += 1
                self._event("gateway_hedge_reject",
                            request=fl.handle.request.request_id,
                            tenant=fl.tenant, reason="stream",
                            after_s=round(now - fl.dispatch_t, 4))
                # stamp so the sweep doesn't re-refuse every tick
                fl.dispatch_t = now
                continue
            by_index = {c.index: c for c in self.cells if c.alive()}
            target = next(
                (by_index[i] for i in fl.rank
                 if i in by_index and i != fl.cell.index
                 and by_index[i].inflight < by_index[i].capacity),
                None)
            if target is None:
                continue
            sent = self._send(fl, target, now)
            if sent is None:
                continue
            fl.hedge_cell = target
            fl.hedge_handle = sent
            self.hedges += 1
            self._event("gateway_hedge",
                        request=fl.handle.request.request_id,
                        tenant=fl.tenant, cell=target.name,
                        after_s=round(now - fl.dispatch_t, 4))

    def _pump(self) -> None:
        last_hedge = 0.0
        while not self._stop.is_set():
            try:
                now = self.clock()
                self._sweep_dead_cells()
                self._sweep_flights(now)
                self._dispatch(now)
                if now - last_hedge >= self.hedge_check_s:
                    self._sweep_hedges(now)
                    last_hedge = now
            except Exception as e:   # noqa: BLE001 — the pump is the
                # gateway's heart; log the beat that failed, keep going
                self._event("gateway_pump_error", error=repr(e))
            self._stop.wait(self.tick_s)

    # -- observability -------------------------------------------------

    def health(self) -> dict:
        alive = [c.name for c in self.cells if c.alive()]
        return {"ok": bool(alive), "cells": len(self.cells),
                "alive_cells": alive}

    def stats(self) -> dict:
        cells = []
        fleet: Dict[str, int] = {k: 0 for k, _ in _FEDERATED_COUNTERS}
        for c in self.cells:
            rec = {"cell": c.name, "alive": c.alive(),
                   "inflight": c.inflight, "capacity": c.capacity,
                   "routed": c.routed}
            if c.alive():
                try:
                    s = c.server.stats()
                    for key, _ in _FEDERATED_COUNTERS:
                        rec[key] = int(s.get(key, 0) or 0)
                        fleet[key] += rec[key]
                except Exception:   # noqa: BLE001 — a dying cell's
                    pass            # stats must not fail the scrape
            cells.append(rec)
        hits = fleet["prefix_hits"]
        done = fleet["completed"]
        out = {
            "cells": cells,
            "alive_cells": sum(1 for c in self.cells if c.alive()),
            "queue_depth": self.queue.depth(),
            "routed": self.routed,
            "spills": self.spills,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "hedge_stream_rejects": self.hedge_stream_rejects,
            "streams_active": sum(
                1 for fl in list(self._flights.values())
                if fl.sinks and not fl.sinks[0].done),
            "replays": self.replays,
            "cell_downs": self.cell_downs,
            "completed": self.completed,
            "expired": self.expired,
            "rejected": self.queue.rejected,
            "fleet": fleet,
            "fleet_prefix_hit_rate": round(hits / max(done, 1), 4),
            "virtual_time": self.queue.virtual_time(),
        }
        if self.tenants is not None:
            out["tenants"] = self.tenants.stats()
        return out

    def metrics_text(self) -> str:
        """One scrape for the whole fleet: gateway counters, per-tenant
        counters, per-tenant latency histograms, and the FEDERATED cell
        counters — each cell's value as a ``cell``-labeled sample plus
        the unlabeled fleet sum, which equals what the cells' own
        /stats report (pinned by test)."""
        stats = self.stats()
        counters = [
            ("dalle_gateway_routed_total",
             "Requests dispatched to cells (hedges excluded)",
             [(None, self.routed)]),
            ("dalle_gateway_spills_total",
             "Dispatches that broke prefix affinity (saturated cell)",
             [(None, self.spills)]),
            ("dalle_gateway_hedges_total",
             "Speculative duplicate sends past the SLO-tier threshold",
             [(None, self.hedges)]),
            ("dalle_gateway_hedge_stream_rejects_total",
             "Hedges refused because the flight is a live stream",
             [(None, self.hedge_stream_rejects)]),
            ("dalle_gateway_replays_total",
             "Zero-loss replays after a cell death or reject",
             [(None, self.replays)]),
            ("dalle_gateway_cell_downs_total",
             "Whole-cell fences", [(None, self.cell_downs)]),
            ("dalle_gateway_requests_completed_total",
             "Requests the gateway fulfilled ok",
             [(None, self.completed)]),
        ]
        if self.tenants is not None:
            ts = self.tenants.stats()
            for key, name, help_text in (
                    ("admitted", "dalle_gateway_tenant_admitted_total",
                     "Requests admitted past tenant quotas"),
                    ("throttled",
                     "dalle_gateway_tenant_throttled_total",
                     "Typed 429 refusals (rate/token/page quota)"),
                    ("completed",
                     "dalle_gateway_tenant_completed_total",
                     "Requests completed per tenant")):
                counters.append((name, help_text,
                                 [({"tenant": t}, rec[key])
                                  for t, rec in sorted(ts.items())]))
        for key, name in _FEDERATED_COUNTERS:
            samples = [({"cell": rec["cell"]}, rec[key])
                       for rec in stats["cells"] if key in rec]
            samples.append((None, stats["fleet"][key]))
            counters.append(
                (name, f"Federated across cells ({key})", samples))
        gauges = [
            ("dalle_gateway_queue_depth",
             "Requests waiting in the weighted-fair queue",
             [(None, stats["queue_depth"])]),
            ("dalle_gateway_alive_cells", "Cells currently serving",
             [(None, stats["alive_cells"])]),
            ("dalle_gateway_streams_active",
             "Gateway flights with a live SSE/token stream",
             [(None, stats["streams_active"])]),
            ("dalle_gateway_cell_inflight",
             "Gateway-tracked in-flight requests per cell",
             [({"cell": rec["cell"]}, rec["inflight"])
              for rec in stats["cells"]]),
        ]
        if self.tenants is not None:
            gauges.append((
                "dalle_gateway_tenant_pages_in_flight",
                "Fleet-wide mapped-page reservations per tenant",
                [({"tenant": t}, rec["pages_in_flight"])
                 for t, rec in sorted(self.tenants.stats().items())]))
        return self.registry.render(counters=counters, gauges=gauges)


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------

def make_gateway_http_server(gateway: Gateway, host: str = "127.0.0.1",
                             port: int = 8000,
                             request_timeout_s: float = 600.0):
    """The fleet's HTTP surface: ``POST /generate`` (API key via
    ``Authorization: Bearer`` or ``X-API-Key``; 401/429 with
    Retry-After on the typed tenancy ladder), ``GET /stats`` /
    ``/healthz`` / ``/metrics`` / ``/tenants``, and the authenticated
    ``POST /admin/tenants`` hot reload."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from dalle_pytorch_tpu.serve import server as _srv

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _send(self, code: int, body: dict, headers=()) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(req, (dict, list)):
                raise ValueError("body must be JSON")
            return req

        def do_GET(self):
            if self.path == "/healthz":
                body = gateway.health()
                self._send(200 if body["ok"] else 503, body)
            elif self.path == "/stats":
                self._send(200, gateway.stats())
            elif self.path == "/metrics":
                data = gateway.metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/tenants":
                t = gateway.tenants
                self._send(200, {"tenants": t.stats()
                                 if t is not None else {}})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def _admin_tenants(self):
            if not auth.check_http(self.headers, gateway.admin_token):
                self._send(401, {"error": "bad admin token"})
                return
            if gateway.tenants is None:
                self._send(409, {"error": "gateway has no tenant "
                                          "table to reload"})
                return
            try:
                self._send(200, gateway.tenants.reload(self._body()))
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})

        def do_POST(self):
            if self.path == "/admin/tenants":
                self._admin_tenants()
                return
            if self.path != "/generate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                req = self._body()
                codes = req.get("codes")
                if not codes:
                    raise ValueError("need non-empty 'codes'")
                kwargs = {k: req[k] for k in
                          ("seed", "temperature", "filter_thres",
                           "top_p", "priority", "deadline_s",
                           "cfg_scale", "stream", "n_samples",
                           "image_seq_len_override") if k in req}
                handle = gateway.submit(
                    codes, api_key=auth.http_token(
                        self.headers, "X-API-Key"), **kwargs)
            except T.AuthError as e:
                self._send(401, e.record)
                return
            except T.TenantThrottled as e:
                self._send(429, e.record, headers=(
                    ("Retry-After",
                     str(max(int(e.retry_after_s + 0.999), 1))),))
                return
            except S.InvalidRequest as e:
                self._send(400, e.record)
                return
            except S.QueueClosed as e:
                self._send(503, e.record)
                return
            except S.ServeRejected as e:
                self._send(429, e.record)
                return
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})
                return
            sink = getattr(handle, "sink", None)
            if sink is not None:
                self._stream_sse(handle, sink)
                return
            try:
                result = handle.result(timeout=request_timeout_s)
            except TimeoutError as e:
                self._send(504, {"error": str(e)})
                return
            self._send(_srv._HTTP_STATUS.get(result.status, 500),
                       _srv._result_body(result))

        def _stream_sse(self, handle, sink) -> None:
            """Same SSE contract as the cell server's facade (event
            framing in docs/SERVING.md): a torn connection fulfils the
            gateway handle cancelled, and the flight sweep cancels the
            cell-side arm so the engine reaps its slots."""
            from dalle_pytorch_tpu.serve import stream as stream_mod
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            try:
                for ev in sink.events(heartbeat_s=5.0):
                    self.wfile.write(stream_mod.sse_bytes(ev))
                    self.wfile.flush()
                result = handle.result(timeout=request_timeout_s)
                self.wfile.write(stream_mod.sse_bytes(
                    {"event": "result", **_srv._result_body(result)}))
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                handle.fulfill(S.Result(
                    status=S.CANCELLED,
                    request_id=handle.request.request_id,
                    reason="client disconnected mid-stream"))
            except TimeoutError:
                pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    return httpd


def serve_gateway_http(gateway: Gateway, host: str = "127.0.0.1",
                       port: int = 8000) -> None:
    """Blocking HTTP loop (cli/serve.py's --gateway main)."""
    httpd = make_gateway_http_server(gateway, host, port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        gateway.close()
