"""Replica-set serving: N supervised engines behind ONE queue, with
zero-loss failover via deterministic replay.

One ``Engine`` is one replica: one compiled decode program over one slot
pool on (ideally) one chip. This module is the layer the ROADMAP's
multi-replica item asks for — a single shared ``RequestQueue`` fronting N
engines (thread-per-engine; the Gemma-on-TPU serving paper's replicated-
engine + health-driven-routing shape, PAPERS.md), where a replica
crashing, hanging, or being drained by an operator costs LATENCY on the
requests it held, never a lost request and never a wrong token.

The key enabler is the same one paged eviction proved (PR 5): sampling
is deterministic in (seed, position) — ``fold_in(request_rng, pos)`` per
step — so an in-flight request is *migratable*. Kill the replica mid-
stream, re-queue the handle at its ORIGINAL arrival position
(``RequestQueue.requeue`` preserves ``queue_seq``), admit it on a
survivor, and the replay emits a token stream bit-identical to an
undisturbed run. The caller cannot tell a failover happened except by
the clock.

Supervision (one supervisor per set, not per request):

  * every replica's serving loop stamps ``Engine.last_heartbeat`` at
    each step and each emit-ring harvest — the harvest ``device_get``
    is the one blocking sync in steady state, so a wedged device stalls
    the stamp exactly where the wedge is;
  * CRASH: the replica loop catches the exception, records it, and
    exits; the supervisor notices the dead loop.
    HANG: ``now - last_heartbeat > heartbeat_s`` while the loop thread
    is still "running". Either way the replica is FENCED
    (``Engine.fence()`` — a fenced engine never fulfils a handle, hands
    a completion downstream, or re-queues anything; the wedged thread
    is abandoned, daemon-style, the same move ``resilience.retry``
    makes for an uncancellable pending claim);
  * RECLAIM: the supervisor snapshots the fenced replica's host-side
    bookkeeping — its private queue (routed, not yet admitted) and its
    in-slot handles (``Engine.inflight_handles``) — and re-queues every
    not-yet-done handle into the shared queue at its original arrival
    position for replay. ``RequestHandle.fulfill`` is first-write-wins,
    so even a fenced thread waking at the worst moment cannot race the
    replay with a stale result;
  * BRING-UP: the replica is rebuilt (fresh ``Engine``, fresh private
    queue). Repeated bring-up failure circuit-breaks the replica with
    exponential backoff (``resilience.retry.RetryPolicy.backoff``)
    while the set keeps serving on the survivors — capacity shrinks,
    the shared queue's ``max_depth`` turns the shrinkage into typed
    ``QueueFull`` backpressure at submit, and nothing ever hangs;
  * DRAIN: ``drain_replica(i)`` is the operator's planned-maintenance
    path — identical fence + reclaim, but the replica stays down until
    ``undrain_replica(i)``.

Routing is least-loaded with page-awareness: the router moves requests
from the shared queue into per-replica private queues (``requeue`` with
``count=False`` — a hand-off, not backpressure; the handle keeps its
shared-queue ``queue_seq`` and ``request_id``), preferring the replica
with the most free slot capacity and, among paged engines, one whose
page pool can map the request's prompt span NOW (free pages from the
replica's kv-pool stats break ties).

Like ``Engine``, the set is drivable two ways: ``step_once``/
``run_until_idle`` single-threaded (tests, bench — deterministic, and
the whole steady state still holds under ``guards.no_transfers`` with
one decode compile per replica), or ``start()`` for live traffic
(thread per replica + one control thread for routing/supervision, what
``serve.server`` uses). With more than one jax device visible, replica
i's engine is committed to device ``i % len(devices)`` so the replicas'
fused chunks genuinely overlap — on a pod slice that is replica-per-
chip serving; on the CPU fallback it still overlaps the async dispatch.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from dalle_pytorch_tpu.serve import scheduler as S

# replica lifecycle states (``replica_states()`` / ``stats()``)
RUNNING = "running"
BROKEN = "broken"        # circuit open: waiting out the bring-up backoff
DRAINED = "drained"      # operator drain: down until undrain_replica()

_COUNTERS = ("tokens_decoded", "decode_steps", "harvests",
             "occupancy_sum", "completed", "expired",
             "decode_traces", "prefill_traces", "evicted")


class _Replica:
    """One supervised slot of the set: the engine + its private queue,
    its loop thread (threaded mode), and the supervisor's bookkeeping
    (lifecycle state, consecutive bring-up failures, backoff clock)."""

    __slots__ = ("index", "state", "engine", "queue", "thread", "stop",
                 "device", "attempt", "bringups", "next_bringup_t",
                 "last_error", "dead")

    def __init__(self, index: int, device=None):
        self.index = index
        self.state = BROKEN          # until the first bring-up succeeds
        self.engine = None
        self.queue: Optional[S.RequestQueue] = None
        self.thread: Optional[threading.Thread] = None
        self.stop: Optional[threading.Event] = None
        self.device = device
        self.attempt = 0             # consecutive bring-up failures
        self.bringups = 0            # lifetime bring-up calls (faults)
        self.next_bringup_t = 0.0
        self.last_error = ""
        self.dead = False            # loop thread recorded a crash


class ReplicaSet:
    """N supervised ``Engine`` replicas behind one shared
    ``scheduler.RequestQueue``. Presents the same drive surface as a
    single engine (``step_once`` / ``run_until_idle`` / ``idle`` /
    ``stats`` plus the counters ``bench._serve_load_point`` reads), so
    everything that can drive an engine can drive a set."""

    def __init__(self, params: dict, cfg, queue: S.RequestQueue, *,
                 replicas: int = 2,
                 num_slots: int = 4,
                 chunk_steps: int = 8,
                 prefill_buckets=None,
                 complete: Optional[Callable] = None,
                 metrics=None, log_every: int = 0,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 heartbeat_s: float = 5.0,
                 bringup_policy=None,
                 place_on_devices: bool = True,
                 idle_sleep_s: float = 0.002):
        import jax

        from dalle_pytorch_tpu.resilience import retry as rretry

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.params = params
        self.cfg = cfg
        self.queue = queue
        self.n_replicas = int(replicas)
        self.complete = complete
        self.metrics = metrics
        self.clock = clock
        self.heartbeat_s = float(heartbeat_s)
        self.kv = str(kv)
        self._engine_kwargs = dict(
            num_slots=num_slots, chunk_steps=chunk_steps,
            prefill_buckets=prefill_buckets, metrics=metrics,
            log_every=log_every, quantize_cache=quantize_cache,
            kv=kv, page_size=page_size, num_pages=num_pages)
        # circuit-breaker backoff between bring-up attempts; serving
        # wants short first retries and a firm cap, not training's
        # minutes-scale defaults
        self.bringup_policy = bringup_policy or rretry.RetryPolicy(
            max_attempts=1, deadline_s=None, base_backoff_s=0.5,
            backoff_multiplier=2.0, max_backoff_s=30.0, jitter=0.0)
        self._idle_sleep_s = float(idle_sleep_s)

        devices = jax.devices()
        self._placed = place_on_devices and len(devices) > 1
        self.replicas: List[_Replica] = []
        for i in range(self.n_replicas):
            dev = devices[i % len(devices)] if self._placed else None
            self.replicas.append(_Replica(i, device=dev))

        # supervisor counters + retired-engine counter base: a fenced
        # engine's numbers are folded in here at reclaim time (minus the
        # reclaimed requests' harvested prefixes — replay re-credits
        # every token, the same distinct-delivered-tokens discipline as
        # paged eviction), so the set's aggregates survive failovers
        self._retired = {k: 0 for k in _COUNTERS}
        self.failovers = 0
        self.reclaimed = 0
        self.expired = 0             # router-side queued-deadline reaps
        self.bringup_failures = 0
        self._ctl_lock = threading.Lock()
        self._started = False
        self._ctl_thread: Optional[threading.Thread] = None
        self._ctl_stop = threading.Event()
        self._t_start: Optional[float] = None

        now = self.clock()
        for r in self.replicas:
            self._bring_up(r, now)

    # -- events -------------------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self.metrics is not None:
            try:
                self.metrics.event(**S.structured_event(kind, **fields))
            except Exception:   # noqa: BLE001 — observability must never
                pass            # take down supervision

    # -- bring-up / circuit breaker -----------------------------------------

    def _bring_up(self, r: _Replica, now: float) -> bool:
        """One bring-up attempt: fresh private queue + fresh Engine (the
        old pair, if any, was fenced and drained at reclaim — reusing
        the drained queue would cancel the NEW engine's evictions).
        Failure schedules the next attempt with exponential backoff;
        the replica stays circuit-broken (BROKEN) in between."""
        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.serve.engine import Engine

        attempt = r.bringups
        r.bringups += 1
        try:
            faults.on_replica_bringup(r.index, attempt)
            queue = S.RequestQueue(
                max_depth=4 * self._engine_kwargs["num_slots"] + 8,
                clock=self.clock)
            engine = Engine(self.params, self.cfg, queue,
                            complete=self.complete, clock=self.clock,
                            device=r.device, **self._engine_kwargs)
        except Exception as e:  # noqa: BLE001 — circuit-break, don't die
            r.attempt += 1
            self.bringup_failures += 1
            delay = self.bringup_policy.backoff(min(r.attempt - 1, 20))
            r.next_bringup_t = now + delay
            r.last_error = repr(e)
            r.state = BROKEN
            self._event("serve_replica_bringup_fail", replica=r.index,
                        attempt=attempt, consecutive=r.attempt,
                        backoff_s=round(delay, 3), error=repr(e))
            return False
        # an orphan is a handle the fenced engine popped but never
        # admitted (fence landed mid-step): back to the shared queue
        engine.on_fenced_orphan = \
            lambda h: self.queue.requeue(h)
        r.engine, r.queue = engine, queue
        r.attempt = 0
        r.dead = False
        r.last_error = ""
        r.stop = threading.Event()
        r.state = RUNNING
        self._event("serve_replica_up", replica=r.index,
                    bringups=r.bringups, device=str(r.device))
        if self._started:
            self._spawn(r)
        return True

    # -- fencing and reclaim (failover / drain) -----------------------------

    def _fence_and_reclaim(self, r: _Replica, now: float,
                           reason: str) -> int:
        """Fence the replica's engine, then reclaim every request it
        held — private queue first (routed, never admitted), then the
        in-slot handles — back into the shared queue at their original
        arrival positions for deterministic replay. Fencing comes FIRST:
        from that point the old engine cannot fulfil, complete, or
        requeue anything, so the reclaim sweep is the single owner of
        these handles (a wedge waking later hits the fence, and
        ``fulfill`` being first-write-wins closes the last window)."""
        eng, q = r.engine, r.queue
        r.engine, r.queue, r.thread = None, None, None
        if r.stop is not None:
            r.stop.set()
        reclaimed = 0
        if eng is not None:
            eng.fence()
            # a crashed/exited loop left the lock free and the hang
            # fault sleeps outside it, so this normally succeeds; a
            # thread truly wedged INSIDE a step keeps the lock — the
            # snapshot below is host-side bookkeeping only, safe to
            # read anyway, and the fence already disarmed the wedge
            got = eng._lock.acquire(timeout=0.2)
            try:
                queued = q.drain() if q is not None else []
                slots = [s for s in list(eng.slots) if s is not None]
                # inflight covers the slots AND any mid-admission
                # handles a thread wedged inside the admission compile
                # holds in step locals (engine._admitting)
                inflight = eng.inflight_handles()
            finally:
                if got:
                    eng._lock.release()
            # fold the dead engine's counters into the set's base,
            # un-crediting reclaimed requests' harvested prefixes: the
            # replay re-credits every token, and the aggregate must
            # keep counting DISTINCT delivered tokens (same discipline
            # as paged eviction's un-credit)
            retire = {k: getattr(eng, k, 0) for k in _COUNTERS}
            for s in slots:
                retire["tokens_decoded"] -= len(s.emitted)
                retire["occupancy_sum"] -= len(s.emitted)
            for k in _COUNTERS:
                self._retired[k] += retire[k]
            seen: set = set()
            for h in queued + inflight:
                rid = h.request.request_id
                if h.done() or rid in seen:
                    continue
                seen.add(rid)
                # original arrival position: zero-loss AND no
                # queue-jumping — a replayed request neither loses
                # its place nor steals anyone else's
                self.queue.requeue(h)
                reclaimed += 1
        self.reclaimed += reclaimed
        self._event("serve_replica_fenced", replica=r.index,
                    reason=reason, reclaimed=reclaimed)
        return reclaimed

    def _failover(self, r: _Replica, now: float, reason: str) -> None:
        self.failovers += 1
        self._fence_and_reclaim(r, now, reason)
        r.state = BROKEN
        r.next_bringup_t = now          # first restart attempt is free;
        #                                 backoff only after it fails

    # -- operator drain -----------------------------------------------------

    def drain_replica(self, index: int,
                      reason: str = "operator drain") -> int:
        """Planned maintenance: fence + reclaim (in-flight work replays
        on the survivors, zero requests lost) and hold the replica DOWN
        until ``undrain_replica``. Returns the number reclaimed."""
        with self._ctl_lock:
            r = self.replicas[index]
            n = self._fence_and_reclaim(r, self.clock(), reason)
            r.state = DRAINED
            return n

    def undrain_replica(self, index: int) -> bool:
        """Bring a drained replica back into routing (one bring-up
        attempt now; failure re-enters the circuit-breaker path)."""
        with self._ctl_lock:
            r = self.replicas[index]
            if r.state != DRAINED:
                return False
            return self._bring_up(r, self.clock())

    # -- supervision --------------------------------------------------------

    def _check_replicas(self, now: float) -> bool:
        """One supervision sweep: crashed loops and missed heartbeats
        are fenced + reclaimed; circuit-broken replicas past their
        backoff get a bring-up attempt. Hang detection applies only to
        replicas with a live loop THREAD — in single-threaded drive the
        driver itself is the loop, so a hang would block the driver,
        and crashes surface synchronously in ``step_once``."""
        did = False
        for r in self.replicas:
            if r.state == RUNNING:
                if r.dead:
                    self._failover(r, now,
                                   reason=f"crash: {r.last_error}")
                    did = True
                elif r.thread is not None and not r.thread.is_alive():
                    self._failover(r, now, reason="loop thread died")
                    did = True
                elif r.thread is not None and r.engine is not None \
                        and not r.engine.compiling \
                        and now - r.engine.last_heartbeat \
                        > self.heartbeat_s:
                    # ``compiling`` exempts a known first-call trace/
                    # compile (seconds on a cold cache) from the hang
                    # deadline — a healthy replica mid-compile must not
                    # be fenced for being slow to warm up
                    self._failover(
                        r, now,
                        reason=f"missed heartbeat "
                               f"(> {self.heartbeat_s:g}s: hang)")
                    did = True
            elif r.state == BROKEN and now >= r.next_bringup_t:
                did = self._bring_up(r, now) or did
        return did

    # -- routing ------------------------------------------------------------

    def _expire(self, h: S.RequestHandle, now: float) -> None:
        req = h.request
        self.expired += 1
        self._event("serve_deadline", request_id=req.request_id,
                    where="queued", deadline_s=req.deadline_s,
                    waited_s=round(now - req.submit_t, 4))
        h.fulfill(S.Result(
            status=S.DEADLINE_EXCEEDED, request_id=req.request_id,
            reason=f"deadline_s={req.deadline_s:g} exceeded (queued)",
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _capacity(self, r: _Replica) -> int:
        return max(0, r.engine.num_slots - r.engine.active_slots()
                   - r.queue.depth())

    def _pick(self, cands: List[_Replica], caps: dict,
              h: S.RequestHandle) -> _Replica:
        """Least-loaded with page-awareness: most free slot capacity
        first; among paged replicas, one whose pool can map the
        request's prompt span NOW beats one that would defer it, and
        free pages break remaining ties."""
        from dalle_pytorch_tpu.serve import kv_pool as KV

        def score(r: _Replica):
            eng = r.engine
            fits, free_pages = True, 0
            if eng.kv == "paged":
                free_pages = eng.alloc.free
                try:
                    need = KV.pages_for(
                        S.bucket_for(len(h.request.codes), eng.buckets),
                        eng.page_size)
                    fits = free_pages >= need
                except ValueError:
                    # an over-long prompt buckets nowhere; the engine's
                    # admission turns it into a typed error result
                    fits = True
            return (fits, caps[r.index], free_pages, -r.index)

        return max(cands, key=score)

    def _route(self, now: float) -> bool:
        """Move ready requests from the shared queue into per-replica
        private queues (a hand-off: ``requeue(count=False)`` keeps the
        handle's shared-queue identity and arrival position). Queued
        deadline expiries are reaped here on EVERY sweep — even with
        zero live replicas, a dead entry must get its typed result."""
        live = [r for r in self.replicas
                if r.state == RUNNING and r.engine is not None]
        caps = {r.index: self._capacity(r) for r in live}
        total = sum(caps.values())
        ready, expired = self.queue.pop_ready(total, now)
        for h in expired:
            self._expire(h, now)
        for h in ready:
            cands = [r for r in live if caps[r.index] > 0]
            r = self._pick(cands, caps, h)
            caps[r.index] -= 1
            r.queue.requeue(h, count=False)
        return bool(ready or expired)

    # -- the replica loop (threaded mode) -----------------------------------

    def _spawn(self, r: _Replica) -> None:
        r.thread = threading.Thread(
            target=self._run_replica, args=(r, r.engine, r.stop),
            daemon=True, name=f"serve-replica-{r.index}")
        r.thread.start()

    def _run_replica(self, r: _Replica, engine, stop) -> None:
        """One replica's serving loop. A step exception is a CRASH —
        recorded for the supervisor, loop exits (contrast the single-
        engine ``Engine.run``, which fails the in-slot requests in
        place: here the supervisor replays them instead, so the callers
        get their exact tokens, not typed errors). A fence (failover
        decided while this thread was wedged) ends the loop on the next
        iteration."""
        from dalle_pytorch_tpu.resilience import faults
        while not stop.is_set() and not engine.fenced:
            try:
                faults.on_replica_chunk(
                    r.index, engine.decode_steps // engine.chunk_steps)
                busy = engine.step_once()
            except Exception as e:  # noqa: BLE001 — supervised crash
                if engine.fenced or r.engine is not engine:
                    # a ZOMBIE crashing: this engine was already fenced
                    # and replaced (e.g. a wedge that finally errored
                    # out) — its requests were reclaimed long ago, and
                    # flagging r.dead now would fail over the healthy
                    # replacement that owns r
                    return
                r.last_error = repr(e)
                r.dead = True
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                return
            if not busy and engine.idle():
                stop.wait(self._idle_sleep_s)

    def _run_control(self, stop: threading.Event) -> None:
        """Routing + supervision loop (threaded mode)."""
        while not stop.is_set():
            now = self.clock()
            with self._ctl_lock:
                busy = self._check_replicas(now)
                busy = self._route(now) or busy
            stop.wait(0.0005 if busy else self._idle_sleep_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaSet":
        """Threaded mode: one loop thread per live replica plus the
        control thread (routing + supervision)."""
        self._started = True
        if self._t_start is None:       # threaded mode never steps
            self._t_start = self.clock()  # sync, so stamp elapsed here
        for r in self.replicas:
            if r.state == RUNNING and r.thread is None:
                self._spawn(r)
        self._ctl_stop = threading.Event()
        self._ctl_thread = threading.Thread(
            target=self._run_control, args=(self._ctl_stop,),
            daemon=True, name="serve-replica-control")
        self._ctl_thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Stop supervision, then every replica loop, joining each with
        its share of the deadline. A replica that OUTLIVES its join
        (wedged in a step) is fenced so it can never fulfil or requeue
        later; either way its private queue is drained and every
        still-open handle — queued or in-slot — is fulfilled
        ``cancelled`` lock-free (first-write-wins makes the late-waker
        race harmless). Callers are never stranded."""
        t0 = time.perf_counter()
        self._ctl_stop.set()
        if self._ctl_thread is not None:
            self._ctl_thread.join(timeout)
        with self._ctl_lock:
            for r in self.replicas:
                if r.stop is not None:
                    r.stop.set()
            for r in self.replicas:
                if r.thread is not None:
                    left = max(0.1, timeout - (time.perf_counter() - t0))
                    r.thread.join(left / max(len(self.replicas), 1))
            for r in self.replicas:
                eng, q = r.engine, r.queue
                if r.thread is not None and r.thread.is_alive() \
                        and eng is not None:
                    eng.fence()
                handles = []
                if q is not None:
                    handles.extend(q.drain())
                if eng is not None:
                    handles.extend(eng.inflight_handles())
                for h in handles:
                    if not h.done():
                        h.fulfill(S.Result(
                            status=S.CANCELLED,
                            request_id=h.request.request_id,
                            reason="server shutdown"))

    # -- single-threaded drive (tests, bench) -------------------------------

    def step_once(self) -> bool:
        """One set iteration: supervise (bring-ups, crash cleanup),
        route, then step every live replica once. Crashes fail over
        INLINE — the same fence/reclaim/replay path the threaded
        supervisor takes, just synchronously."""
        from dalle_pytorch_tpu.resilience import faults
        now = self.clock()
        if self._t_start is None:
            self._t_start = now
        with self._ctl_lock:
            did = self._check_replicas(now)
            did = self._route(now) or did
        for r in list(self.replicas):
            if r.state != RUNNING or r.engine is None:
                continue
            eng = r.engine
            try:
                faults.on_replica_chunk(
                    r.index, eng.decode_steps // eng.chunk_steps)
                did = eng.step_once() or did
            except Exception as e:  # noqa: BLE001 — supervised crash
                r.last_error = repr(e)
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                with self._ctl_lock:
                    self._failover(r, self.clock(),
                                   reason=f"crash: {e!r}")
                did = True
        return did

    def idle(self) -> bool:
        if self.queue.depth() > 0:
            return False
        for r in self.replicas:
            if r.queue is not None and r.queue.depth() > 0:
                return False
            if r.engine is not None and (r.engine.active_slots() > 0
                                         or r.engine._pending):
                return False
        return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            busy = self.step_once()
            if not busy and self.idle():
                return
        raise RuntimeError(
            f"replica set did not go idle in {max_steps} steps")

    # -- aggregate counters (bench._serve_load_point's surface) -------------

    def _agg(self, name: str) -> int:
        return self._retired[name] + sum(
            getattr(r.engine, name, 0) for r in self.replicas
            if r.engine is not None)

    @property
    def tokens_decoded(self) -> int:
        return self._agg("tokens_decoded")

    @property
    def decode_steps(self) -> int:
        return self._agg("decode_steps")

    @property
    def harvests(self) -> int:
        return self._agg("harvests")

    @property
    def occupancy_sum(self) -> int:
        return self._agg("occupancy_sum")

    @property
    def completed(self) -> int:
        return self._agg("completed")

    # -- observability ------------------------------------------------------

    def alive(self) -> bool:
        """True while at least one replica serves (healthz contract:
        503 only when ALL are dead)."""
        for r in self.replicas:
            if r.state != RUNNING or r.engine is None:
                continue
            if r.thread is None or r.thread.is_alive():
                return True
        return False

    def replica_states(self) -> List[dict]:
        now = self.clock()
        out = []
        for r in self.replicas:
            alive = r.state == RUNNING and r.engine is not None and \
                (r.thread is None or r.thread.is_alive())
            rec = {"replica": r.index, "state": r.state, "alive": alive,
                   "bringups": r.bringups}
            if r.engine is not None:
                rec["heartbeat_age_s"] = round(
                    max(now - r.engine.last_heartbeat, 0.0), 4)
            if r.last_error:
                rec["last_error"] = r.last_error
            out.append(rec)
        return out

    def decode_compiles_per_replica(self) -> List[int]:
        """Each LIVE replica's decode-program trace count — the
        one-compile-per-replica contract bench_serve asserts (a
        replaced engine is a fresh program, counted on its own)."""
        return [r.engine.decode_traces for r in self.replicas
                if r.engine is not None]

    def stats(self) -> dict:
        elapsed = None if self._t_start is None \
            else max(self.clock() - self._t_start, 1e-9)
        live = [r for r in self.replicas if r.engine is not None]
        per = []
        for r in self.replicas:
            rec = {"replica": r.index, "state": r.state}
            if r.engine is not None:
                e = r.engine
                rec.update({
                    "active_slots": e.active_slots(),
                    "queued": r.queue.depth() if r.queue else 0,
                    "decode_compiles": e.decode_traces,
                    "prefill_compiles": e.prefill_traces,
                    "completed": e.completed,
                    "tokens_decoded": e.tokens_decoded,
                })
                if e.kv == "paged":
                    rec["pages_free"] = e.alloc.free
            per.append(rec)
        tokens = self.tokens_decoded
        steps = self.decode_steps
        return {
            "replicas": self.n_replicas,
            "alive_replicas": sum(
                1 for r in self.replicas
                if r.state == RUNNING and r.engine is not None),
            "kv": self.kv,
            "queue_depth": self.queue.depth() + sum(
                r.queue.depth() for r in live if r.queue is not None),
            "num_slots": sum(r.engine.num_slots for r in live),
            "active_slots": sum(r.engine.active_slots() for r in live),
            "chunk_steps": self._engine_kwargs["chunk_steps"],
            "decode_steps": steps,
            "tokens_decoded": tokens,
            "tokens_per_s": (round(tokens / elapsed, 2)
                             if elapsed else 0.0),
            "mean_occupancy": round(self.occupancy_sum / max(steps, 1),
                                    3),
            "completed": self.completed,
            "expired": self._agg("expired") + self.expired,
            "rejected": self.queue.rejected,
            "requeued": self.queue.requeued,
            "decode_compiles": self._agg("decode_traces"),
            "prefill_compiles": self._agg("prefill_traces"),
            "harvests": self.harvests,
            "host_round_trips_per_token": round(
                self.harvests / max(tokens, 1), 6),
            "failovers": self.failovers,
            "reclaimed": self.reclaimed,
            "bringup_failures": self.bringup_failures,
            "evicted": self._agg("evicted"),
            "per_replica": per,
        }
