"""Replica-set serving: N supervised engines behind ONE queue, with
zero-loss failover via deterministic replay.

One ``Engine`` is one replica: one compiled decode program over one slot
pool on (ideally) one chip. This module is the layer the ROADMAP's
multi-replica item asks for — a single shared ``RequestQueue`` fronting N
engines (thread-per-engine; the Gemma-on-TPU serving paper's replicated-
engine + health-driven-routing shape, PAPERS.md), where a replica
crashing, hanging, or being drained by an operator costs LATENCY on the
requests it held, never a lost request and never a wrong token.

The key enabler is the same one paged eviction proved (PR 5): sampling
is deterministic in (seed, position) — ``fold_in(request_rng, pos)`` per
step — so an in-flight request is *migratable*. Kill the replica mid-
stream, re-queue the handle at its ORIGINAL arrival position
(``RequestQueue.requeue`` preserves ``queue_seq``), admit it on a
survivor, and the replay emits a token stream bit-identical to an
undisturbed run. The caller cannot tell a failover happened except by
the clock.

Supervision (one supervisor per set, not per request):

  * every replica's serving loop stamps ``Engine.last_heartbeat`` at
    each step and each emit-ring harvest — the harvest ``device_get``
    is the one blocking sync in steady state, so a wedged device stalls
    the stamp exactly where the wedge is;
  * CRASH: the replica loop catches the exception, records it, and
    exits; the supervisor notices the dead loop.
    HANG: ``now - last_heartbeat > heartbeat_s`` while the loop thread
    is still "running". Either way the replica is FENCED
    (``Engine.fence()`` — a fenced engine never fulfils a handle, hands
    a completion downstream, or re-queues anything; the wedged thread
    is abandoned, daemon-style, the same move ``resilience.retry``
    makes for an uncancellable pending claim);
  * RECLAIM: the supervisor snapshots the fenced replica's host-side
    bookkeeping — its private queue (routed, not yet admitted) and its
    in-slot handles (``Engine.inflight_handles``) — and re-queues every
    not-yet-done handle into the shared queue at its original arrival
    position for replay. ``RequestHandle.fulfill`` is first-write-wins,
    so even a fenced thread waking at the worst moment cannot race the
    replay with a stale result;
  * BRING-UP: the replica is rebuilt (fresh ``Engine``, fresh private
    queue). Repeated bring-up failure circuit-breaks the replica with
    exponential backoff (``resilience.retry.RetryPolicy.backoff``)
    while the set keeps serving on the survivors — capacity shrinks,
    the shared queue's ``max_depth`` turns the shrinkage into typed
    ``QueueFull`` backpressure at submit, and nothing ever hangs;
  * DRAIN: ``drain_replica(i)`` is the operator's planned-maintenance
    path — identical fence + reclaim, but the replica stays down until
    ``undrain_replica(i)``.

Routing is least-loaded with page-awareness: the router moves requests
from the shared queue into per-replica private queues (``requeue`` with
``count=False`` — a hand-off, not backpressure; the handle keeps its
shared-queue ``queue_seq`` and ``request_id``), preferring the replica
with the most free slot capacity and, among paged engines, one whose
page pool can map the request's prompt span NOW (free pages from the
replica's kv-pool stats break ties).

Like ``Engine``, the set is drivable two ways: ``step_once``/
``run_until_idle`` single-threaded (tests, bench — deterministic, and
the whole steady state still holds under ``guards.no_transfers`` with
one decode compile per replica), or ``start()`` for live traffic
(thread per replica + one control thread for routing/supervision, what
``serve.server`` uses). With more than one jax device visible, replica
i's engine is committed to device ``i % len(devices)`` so the replicas'
fused chunks genuinely overlap — on a pod slice that is replica-per-
chip serving; on the CPU fallback it still overlaps the async dispatch.

ISOLATION SHAPES. ``isolation='thread'`` (the default) is the above:
replicas are threads sharing this process — cheap, transfer-guardable,
but a segfault in XLA, a host OOM, or a `kill -9` still takes the whole
set down. ``isolation='process'`` runs each replica's engine in a
SPAWNED CHILD PROCESS (own interpreter, own jax client, pinned to its
device — ``serve/worker.py``) behind the typed IPC layer in
``serve/ipc.py``. The fence/reclaim/replay protocol is identical; what
changes is who holds the truth: the parent keeps a SHADOW of every
handle routed to a child (``ChildEngineClient.shadow``) and reclaims
from that, never from the child — a SIGKILLed process answers nothing.
Supervision gains a second liveness signal: child PID liveness with
exit-code/signal decoding (SIGKILL, SIGSEGV, the exit-137 RSS-watchdog
OOM convention) layered on top of the same missed-heartbeat deadline,
where heartbeats are now frames on the pipe rather than a shared-heap
timestamp. A hard-killed child is fenced exactly like a crash or hang:
its pipe is drained for frames written before death (those results
stand), everything still open replays byte-identically on a survivor,
and the dead replica restarts through the same circuit-breaker backoff.

TRANSPORT SHAPES (process isolation only). ``transport='pipe'`` (the
default) carries the frames over a duplex pipe — local children only.
``transport='socket'`` makes isolation HOST-shaped: the parent opens
one dial-in endpoint (``serve/transport.py``'s ``WorkerListener``;
``worker_endpoint`` picks the bind address) and every worker CONNECTS
BACK with an authenticated HELLO (shared token + protocol version +
replica index), then receives its engine spec over the socket. Three
ways a worker comes to exist — a locally spawned child that dials back
(the default), a launcher command per replica (``worker_cmd`` with
``{endpoint}``/``{index}`` placeholders, e.g. an ssh line; the token
travels in the ``DALLE_WORKER_TOKEN`` env var), or a worker an
operator starts BY HAND on another host (``worker_cmd=''``) — and all
three are supervised identically: shadow bookkeeping, heartbeat
deadline, fence→reclaim→replay at original arrival position. A worker
with no local PID is declared dead off its socket (EOF/reset), and the
frame protocol's per-connection sequence numbers + the transport's
torn-frame detection turn every network failure mode — reset
mid-frame, partial frame, stalled link, duplicated or reordered
delivery — into the same typed fence + byte-identical replay a local
`kill -9` gets (docs/SERVING.md 'Host isolation & socket transport').
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve.engine import COUNTERS as _COUNTERS

# replica lifecycle states (``replica_states()`` / ``stats()``)
RUNNING = "running"
BROKEN = "broken"        # circuit open: waiting out the bring-up backoff
DRAINED = "drained"      # operator drain: down until undrain_replica()

ISOLATION_MODES = ("thread", "process")
TRANSPORT_MODES = ("pipe", "socket")


class _Replica:
    """One supervised slot of the set: the engine + its private queue,
    its loop thread (threaded mode), and the supervisor's bookkeeping
    (lifecycle state, consecutive bring-up failures, backoff clock)."""

    __slots__ = ("index", "state", "engine", "queue", "thread", "stop",
                 "device", "attempt", "bringups", "next_bringup_t",
                 "last_error", "dead", "await_ready", "last_exit",
                 "conns")

    def __init__(self, index: int, device=None):
        self.index = index
        self.state = BROKEN          # until the first bring-up succeeds
        self.engine = None
        self.queue: Optional[S.RequestQueue] = None
        self.thread: Optional[threading.Thread] = None
        self.stop: Optional[threading.Event] = None
        self.device = device
        self.attempt = 0             # consecutive bring-up failures
        self.bringups = 0            # lifetime bring-up calls (faults)
        self.next_bringup_t = 0.0
        self.last_error = ""
        self.dead = False            # loop thread recorded a crash
        self.await_ready = False     # process child spawned, READY due
        self.last_exit = ""          # decoded exit of the last child
        self.conns = 0               # workers that reached READY here


class ReplicaSet:
    """N supervised ``Engine`` replicas behind one shared
    ``scheduler.RequestQueue``. Presents the same drive surface as a
    single engine (``step_once`` / ``run_until_idle`` / ``idle`` /
    ``stats`` plus the counters ``bench._serve_load_point`` reads), so
    everything that can drive an engine can drive a set."""

    def __init__(self, params: dict, cfg, queue: S.RequestQueue, *,
                 replicas: int = 2,
                 num_slots: int = 4,
                 chunk_steps: int = 8,
                 prefill_buckets=None,
                 complete: Optional[Callable] = None,
                 metrics=None, log_every: int = 0,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 paged_attn: str = "gather",
                 sparse_reads: bool = False,
                 prefix_cache: bool = False,
                 clock: Callable[[], float] = time.perf_counter,
                 heartbeat_s: float = 5.0,
                 bringup_policy=None,
                 place_on_devices: bool = True,
                 idle_sleep_s: float = 0.002,
                 isolation: str = "thread",
                 child_rss_limit_mb: int = 0,
                 spawn_timeout_s: float = 120.0,
                 compile_grace_s: float = 120.0,
                 transport: str = "pipe",
                 worker_endpoint: str = "127.0.0.1:0",
                 worker_cmd: Optional[str] = None,
                 attach_token: Optional[str] = None,
                 worker_ckpt: Optional[str] = None,
                 worker_use_ema: bool = False,
                 worker_quantize: str = "none",
                 devices_per_replica: int = 1):
        import jax

        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.resilience import retry as rretry

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if isolation not in ISOLATION_MODES:
            raise ValueError(f"isolation must be one of "
                             f"{ISOLATION_MODES}, got {isolation!r}")
        if transport not in TRANSPORT_MODES:
            raise ValueError(f"transport must be one of "
                             f"{TRANSPORT_MODES}, got {transport!r}")
        if transport == "socket" and isolation != "process":
            raise ValueError("transport='socket' requires "
                             "isolation='process' (threads share a "
                             "heap; there is nothing to socket)")
        if worker_cmd is not None and transport != "socket":
            raise ValueError("worker_cmd needs transport='socket' — a "
                             "pipe cannot cross a launcher boundary")
        if worker_ckpt is not None and transport != "socket":
            raise ValueError(
                "worker_ckpt needs transport='socket': its point is "
                "that a worker on ANOTHER host loads weights from its "
                "local checkpoint store instead of receiving pickled "
                "params over the wire")
        self.worker_use_ema = bool(worker_use_ema)
        self.worker_quantize = str(worker_quantize)
        if self.worker_quantize not in ("none", "int8", "int8_kv"):
            raise ValueError(f"worker_quantize must be 'none', 'int8' "
                             f"or 'int8_kv', got {worker_quantize!r}")
        if (self.worker_use_ema or self.worker_quantize != "none") \
                and worker_ckpt is None:
            # these describe the WORKER's local load path; without a
            # ckpt-path spec the parent's (already transformed) params
            # cross the boundary and the flags would silently do
            # nothing — the same misconfiguration hazard as worker_cmd
            raise ValueError(
                "worker_use_ema/worker_quantize transform the "
                "checkpoint a worker loads locally — they need "
                "worker_ckpt (without it, pass params you transformed "
                "yourself)")
        self.devices_per_replica = int(devices_per_replica)
        if self.devices_per_replica < 1:
            raise ValueError(f"devices_per_replica must be >= 1, got "
                             f"{devices_per_replica}")
        if self.devices_per_replica > 1 and paged_attn == "kernel":
            # fail at construction with the typed error, not once per
            # circuit-broken bring-up attempt forever
            from dalle_pytorch_tpu.serve.mesh_engine import \
                MeshPagedAttnError
            from dalle_pytorch_tpu.utils.metrics import structured_event
            raise MeshPagedAttnError(structured_event(
                "serve_mesh_paged_attn_unsupported",
                paged_attn="kernel"))
        # the CLI-harness fault path (DALLE_FAULTS): child plans are cut
        # at spawn time, so the env plan must be live before the first
        # bring-up — no-op when unset or already active
        faults.maybe_activate_from_env()
        self.params = params
        self.cfg = cfg
        self.queue = queue
        self.n_replicas = int(replicas)
        self.complete = complete
        self.metrics = metrics
        self.clock = clock
        self.heartbeat_s = float(heartbeat_s)
        self.kv = str(kv)
        self.isolation = str(isolation)
        self.transport = str(transport)
        self.worker_cmd = worker_cmd
        self.child_rss_limit_mb = int(child_rss_limit_mb)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.compile_grace_s = float(compile_grace_s)
        self.listener = None
        if self.transport == "socket":
            from dalle_pytorch_tpu.serve import transport as T
            host, port = T.parse_endpoint(worker_endpoint)
            self.listener = T.WorkerListener(
                host, port, token=attach_token,
                on_event=(lambda rec: self._event(rec.pop("kind"),
                                                  **rec)))
        self._engine_kwargs = dict(
            num_slots=num_slots, chunk_steps=chunk_steps,
            prefill_buckets=prefill_buckets, metrics=metrics,
            log_every=log_every, quantize_cache=quantize_cache,
            kv=kv, page_size=page_size, num_pages=num_pages,
            paged_attn=paged_attn, sparse_reads=sparse_reads,
            prefix_cache=prefix_cache)
        self.worker_ckpt = worker_ckpt
        if self.isolation == "process":
            import numpy as np
            # what crosses the spawn boundary: a host numpy pytree of
            # the params (one device_get here, one upload in the child
            # — the child owns its own device copy), and a picklable
            # subset of the engine kwargs (the metrics sink stays in
            # the parent; supervision events are parent-side). With
            # worker_ckpt set, NO params cross at all: the spec carries
            # the checkpoint path and each worker loads + validates
            # locally (serve/worker.py) — the attach spec shrinks from
            # the full weight pytree to a string
            self._np_params = None if worker_ckpt is not None \
                else jax.tree.map(np.asarray, params)
            self._child_kwargs = dict(
                num_slots=num_slots, chunk_steps=chunk_steps,
                prefill_buckets=prefill_buckets,
                quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                prefix_cache=prefix_cache)
            # routing needs page math without an Engine in-process:
            # mirror the engine's bucket/page-size resolution
            self._buckets = (S.prefill_buckets(cfg.text_seq_len)
                             if prefill_buckets is None
                             else tuple(sorted(set(
                                 int(b) for b in prefill_buckets))))
            self._page_size = (int(page_size)
                               or min(16, cfg.seq_len)) if kv == "paged" \
                else 0
        # circuit-breaker backoff between bring-up attempts; serving
        # wants short first retries and a firm cap, not training's
        # minutes-scale defaults
        self.bringup_policy = bringup_policy or rretry.RetryPolicy(
            max_attempts=1, deadline_s=None, base_backoff_s=0.5,
            backoff_multiplier=2.0, max_backoff_s=30.0, jitter=0.0)
        self._idle_sleep_s = float(idle_sleep_s)

        devices = jax.devices()
        self._placed = place_on_devices and len(devices) > 1
        self.replicas: List[_Replica] = []
        for i in range(self.n_replicas):
            if self.devices_per_replica > 1 \
                    and self.isolation != "process":
                # replica = mesh SLICE: devices [i*m, (i+1)*m) (wrapped
                # like the single-chip i % n placement when the host
                # holds fewer slices than replicas). A mesh engine is
                # always pinned to its slice — unpinned, every replica
                # would shard over ALL devices and serialize against
                # the others. Process mode resolves the slice in the
                # WORKER from its own jax client (serve/worker.py): a
                # remote worker's devices live on its host, and the
                # parent — possibly a 0-accelerator head node — must
                # not gate construction on holding them locally.
                from dalle_pytorch_tpu.parallel import serve_specs as SS
                dev = SS.slice_devices(devices, i,
                                       self.devices_per_replica)
            else:
                dev = devices[i % len(devices)] if self._placed else None
            self.replicas.append(_Replica(i, device=dev))

        # supervisor counters + retired-engine counter base: a fenced
        # engine's numbers are folded in here at reclaim time (minus the
        # reclaimed requests' harvested prefixes — replay re-credits
        # every token, the same distinct-delivered-tokens discipline as
        # paged eviction), so the set's aggregates survive failovers
        self._retired = {k: 0 for k in _COUNTERS}
        self.failovers = 0
        self.reclaimed = 0
        self.expired = 0             # router-side queued-deadline reaps
        self.bringup_failures = 0
        self._ctl_lock = threading.Lock()
        self._started = False
        self._ctl_thread: Optional[threading.Thread] = None
        self._ctl_stop = threading.Event()
        self._t_start: Optional[float] = None

        now = self.clock()
        for r in self.replicas:
            self._bring_up(r, now)

    # -- events -------------------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self.metrics is not None:
            try:
                self.metrics.event(**S.structured_event(kind, **fields))
            except Exception:   # noqa: BLE001 — observability must never
                pass            # take down supervision

    # -- bring-up / circuit breaker -----------------------------------------

    def _bring_up(self, r: _Replica, now: float) -> bool:
        """One bring-up attempt: fresh private queue + fresh Engine (the
        old pair, if any, was fenced and drained at reclaim — reusing
        the drained queue would cancel the NEW engine's evictions).
        Failure schedules the next attempt with exponential backoff;
        the replica stays circuit-broken (BROKEN) in between."""
        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.serve.engine import Engine

        attempt = r.bringups
        r.bringups += 1
        try:
            faults.on_replica_bringup(r.index, attempt)
            if self.isolation == "process":
                from dalle_pytorch_tpu.serve import ipc
                client = ipc.ChildEngineClient(
                    self._np_params, self.cfg,
                    index=r.index,
                    engine_kwargs=self._child_kwargs,
                    device_index=r.index,
                    place=self._placed,
                    devices_per_replica=self.devices_per_replica,
                    ckpt_path=self.worker_ckpt,
                    ckpt_use_ema=self.worker_use_ema,
                    ckpt_quantize=self.worker_quantize,
                    heartbeat_interval_s=min(
                        max(self.heartbeat_s / 5, 0.01), 0.25),
                    rss_limit_mb=self.child_rss_limit_mb,
                    # hard-fault plans cross the boundary ONCE per
                    # activation per replica (fire-once must outlive
                    # the child — see faults.child_plan_for)
                    fault_plan=faults.child_plan_for(r.index),
                    idle_sleep_s=self._idle_sleep_s,
                    clock=self.clock,
                    on_done=self._child_done,
                    transport=self.transport,
                    listener=self.listener,
                    worker_cmd=self.worker_cmd)
            else:
                queue = S.RequestQueue(
                    max_depth=4 * self._engine_kwargs["num_slots"] + 8,
                    clock=self.clock)
                if self.devices_per_replica > 1:
                    # replica = mesh slice: same Engine surface, params
                    # + KV sharded over this replica's device slice —
                    # which is why nothing else in this module changes
                    from dalle_pytorch_tpu.serve.mesh_engine import \
                        MeshEngine
                    engine = MeshEngine(
                        self.params, self.cfg, queue,
                        complete=self.complete, clock=self.clock,
                        devices=r.device, **self._engine_kwargs)
                else:
                    engine = Engine(self.params, self.cfg, queue,
                                    complete=self.complete,
                                    clock=self.clock,
                                    device=r.device,
                                    **self._engine_kwargs)
        except Exception as e:  # noqa: BLE001 — circuit-break, don't die
            r.attempt += 1
            self.bringup_failures += 1
            delay = self.bringup_policy.backoff(min(r.attempt - 1, 20))
            r.next_bringup_t = now + delay
            r.last_error = repr(e)
            r.state = BROKEN
            self._event("serve_replica_bringup_fail", replica=r.index,
                        attempt=attempt, consecutive=r.attempt,
                        backoff_s=round(delay, 3), error=repr(e))
            return False
        if self.isolation == "process":
            # the spawn is async: the child is importing jax and
            # building its engine. RUNNING means "spawned"; routing is
            # gated on client.ready, and _check_replicas turns a child
            # that dies or stalls before READY into a bring-up failure
            # (with backoff), not a failover — there is nothing to
            # reclaim yet. r.attempt resets when READY lands.
            r.engine, r.queue = client, None
            r.dead = False
            r.await_ready = True
            r.stop = None
            r.state = RUNNING
            return True
        # an orphan is a handle the fenced engine popped but never
        # admitted (fence landed mid-step): back to the shared queue
        engine.on_fenced_orphan = \
            lambda h: self.queue.requeue(h)
        r.engine, r.queue = engine, queue
        r.attempt = 0
        r.dead = False
        r.last_error = ""
        r.stop = threading.Event()
        r.state = RUNNING
        self._event("serve_replica_up", replica=r.index,
                    bringups=r.bringups, device=str(r.device))
        if self._started:
            self._spawn(r)
        return True

    def _child_done(self, handle: S.RequestHandle,
                    result: S.Result) -> None:
        """Completion hand-off for process-mode results (the client's
        ``on_done``): same contract as ``Engine._finish`` — OK results
        flow downstream (postprocess), everything else fulfils the
        handle directly."""
        if result.status == S.OK and self.complete is not None:
            self.complete(handle, result)
        else:
            handle.fulfill(result)

    # -- fencing and reclaim (failover / drain) -----------------------------

    def _fence_and_reclaim(self, r: _Replica, now: float,
                           reason: str) -> int:
        """Fence the replica's engine, then reclaim every request it
        held — private queue first (routed, never admitted), then the
        in-slot handles — back into the shared queue at their original
        arrival positions for deterministic replay. Fencing comes FIRST:
        from that point the old engine cannot fulfil, complete, or
        requeue anything, so the reclaim sweep is the single owner of
        these handles (a wedge waking later hits the fence, and
        ``fulfill`` being first-write-wins closes the last window).

        Process mode inverts one step on purpose: the child is KILLED
        first (SIGKILL — crashed, wedged, or lying, all three deserve
        -9), then the pipe is drained for frames written before death
        (salvaged results stand and are NOT replayed; the final
        snapshot is the last consistent counter state), and only then
        is the client fenced and the shadow reclaimed. Killing before
        salvaging is what makes the drain safe: a dead writer cannot
        extend the stream while we read it."""
        if self.isolation == "process":
            return self._fence_and_reclaim_child(r, now, reason)
        eng, q = r.engine, r.queue
        r.engine, r.queue, r.thread = None, None, None
        if r.stop is not None:
            r.stop.set()
        reclaimed = 0
        if eng is not None:
            eng.fence()
            # a crashed/exited loop left the lock free and the hang
            # fault sleeps outside it, so this normally succeeds; a
            # thread truly wedged INSIDE a step keeps the lock — the
            # snapshot below is host-side bookkeeping only, safe to
            # read anyway, and the fence already disarmed the wedge
            got = eng._lock.acquire(timeout=0.2)
            try:
                queued = q.drain() if q is not None else []
                slots = [s for s in list(eng.slots) if s is not None]
                # inflight covers the slots AND any mid-admission
                # handles a thread wedged inside the admission compile
                # holds in step locals (engine._admitting)
                inflight = eng.inflight_handles()
            finally:
                if got:
                    eng._lock.release()
            # fold the dead engine's counters into the set's base,
            # un-crediting reclaimed requests' harvested prefixes: the
            # replay re-credits every token, and the aggregate must
            # keep counting DISTINCT delivered tokens (same discipline
            # as paged eviction's un-credit)
            retire = {k: getattr(eng, k, 0) for k in _COUNTERS}
            for s in slots:
                retire["tokens_decoded"] -= len(s.emitted)
                retire["occupancy_sum"] -= len(s.emitted)
            for k in _COUNTERS:
                self._retired[k] += retire[k]
            seen: set = set()
            for h in queued + inflight:
                rid = h.request.request_id
                if h.done() or rid in seen:
                    continue
                seen.add(rid)
                # original arrival position: zero-loss AND no
                # queue-jumping — a replayed request neither loses
                # its place nor steals anyone else's
                self.queue.requeue(h)
                reclaimed += 1
        self.reclaimed += reclaimed
        self._event("serve_replica_fenced", replica=r.index,
                    reason=reason, reclaimed=reclaimed)
        return reclaimed

    def _fence_and_reclaim_child(self, r: _Replica, now: float,
                                 reason: str) -> int:
        """The process-mode half of ``_fence_and_reclaim`` (see its
        docstring): kill -> salvage -> fence -> reclaim-from-shadow."""
        client = r.engine
        r.engine, r.queue, r.thread = None, None, None
        r.await_ready = False
        reclaimed = 0
        if client is not None:
            # how the child died, honestly: a child that was already
            # dead when we got here died on its own (signal/OOM/crash
            # — the decoded exit is the story); a child WE are killing
            # (drain, hang, protocol error) must not advertise
            # 'killed by SIGKILL' as if the OS had done it
            died_on_its_own = not client.alive_proc()
            client.hard_kill()
            r.last_exit = (client.exit_desc() if died_on_its_own
                           else f"hard-killed by supervisor ({reason})")
            client.salvage()
            client.fence()
            handles = client.reclaim()
            retire = client.retire_counters(handles)
            for k in _COUNTERS:
                self._retired[k] += retire.get(k, 0)
            for h in handles:
                # original arrival position: zero-loss AND no
                # queue-jumping, same as the thread path
                self.queue.requeue(h)
                reclaimed += 1
        self.reclaimed += reclaimed
        self._event("serve_replica_fenced", replica=r.index,
                    reason=reason, reclaimed=reclaimed,
                    exit=r.last_exit)
        return reclaimed

    def _failover(self, r: _Replica, now: float, reason: str) -> None:
        self.failovers += 1
        self._fence_and_reclaim(r, now, reason)
        r.state = BROKEN
        r.next_bringup_t = now          # first restart attempt is free;
        #                                 backoff only after it fails

    # -- operator drain -----------------------------------------------------

    def drain_replica(self, index: int,
                      reason: str = "operator drain") -> int:
        """Planned maintenance: fence + reclaim (in-flight work replays
        on the survivors, zero requests lost) and hold the replica DOWN
        until ``undrain_replica``. Returns the number reclaimed."""
        with self._ctl_lock:
            r = self.replicas[index]
            n = self._fence_and_reclaim(r, self.clock(), reason)
            r.state = DRAINED
            return n

    def undrain_replica(self, index: int) -> bool:
        """Bring a drained replica back into routing (one bring-up
        attempt now; failure re-enters the circuit-breaker path)."""
        with self._ctl_lock:
            r = self.replicas[index]
            if r.state != DRAINED:
                return False
            return self._bring_up(r, self.clock())

    # -- supervision --------------------------------------------------------

    def _check_replicas(self, now: float) -> bool:
        """One supervision sweep: crashed loops and missed heartbeats
        are fenced + reclaimed; circuit-broken replicas past their
        backoff get a bring-up attempt. Hang detection applies only to
        replicas with a live loop THREAD — in single-threaded drive the
        driver itself is the loop, so a hang would block the driver,
        and crashes surface synchronously in ``step_once``."""
        did = False
        for r in self.replicas:
            if r.state == RUNNING and self.isolation == "process":
                did = self._check_child(r, now) or did
            elif r.state == RUNNING:
                if r.dead:
                    self._failover(r, now,
                                   reason=f"crash: {r.last_error}")
                    did = True
                elif r.thread is not None and not r.thread.is_alive():
                    self._failover(r, now, reason="loop thread died")
                    did = True
                elif r.thread is not None and r.engine is not None \
                        and not r.engine.compiling \
                        and now - r.engine.last_heartbeat \
                        > self.heartbeat_s:
                    # ``compiling`` exempts a known first-call trace/
                    # compile (seconds on a cold cache) from the hang
                    # deadline — a healthy replica mid-compile must not
                    # be fenced for being slow to warm up
                    self._failover(
                        r, now,
                        reason=f"missed heartbeat "
                               f"(> {self.heartbeat_s:g}s: hang)")
                    did = True
            elif r.state == BROKEN and now >= r.next_bringup_t:
                did = self._bring_up(r, now) or did
        return did

    def _check_child(self, r: _Replica, now: float) -> bool:
        """One supervision check of a RUNNING process replica — the two
        liveness signals layered: PID liveness with exit decoding (a
        SIGKILL/SIGSEGV/OOM death answers at the OS level even though
        the child can say nothing), then the missed-heartbeat deadline
        over the frame stream (a process that is alive but silent is
        wedged — it gets hard-killed and fenced like a hang). A child
        that dies BEFORE its READY frame is a bring-up failure, not a
        failover: it never held work, so it re-enters the circuit-
        breaker backoff with nothing to reclaim."""
        c = r.engine
        if c is None:
            return False
        if not c.ready:
            if c.crashed or c.poisoned or not c.alive_proc():
                c.hard_kill()
                self._bringup_fail_async(
                    r, now, f"child died in bring-up: "
                            f"{c.last_error or c.exit_desc()}")
                return True
            if now - c.started_t > self.spawn_timeout_s \
                    and not c.awaiting_operator:
                # an operator-attached worker has no spawn to time out:
                # the slot waits (unroutable, harmless) until a worker
                # dials in, and the deadline starts at attach
                c.hard_kill()
                self._bringup_fail_async(
                    r, now, f"child bring-up exceeded "
                            f"{self.spawn_timeout_s:g}s")
                return True
            return False
        if c.crashed:
            r.last_error = f"crash: {c.last_error}"
            self._failover(r, now, reason=r.last_error)
        elif c.poisoned:
            r.last_error = c.last_error
            self._failover(r, now, reason=r.last_error)
        elif not c.alive_proc():
            r.last_error = f"child exited: {c.exit_desc()}"
            self._failover(r, now, reason=r.last_error)
        else:
            # compiling exempts a child from the tight deadline but not
            # forever: compile_grace_s caps how long "still compiling"
            # is believable without a single frame. The failover reason
            # names the deadline that actually expired.
            if c.compiling:
                deadline, which = (max(self.heartbeat_s,
                                       self.compile_grace_s),
                                   "compile grace")
            else:
                deadline, which = self.heartbeat_s, "heartbeat"
            if now - c.last_heartbeat <= deadline:
                return False
            self._failover(
                r, now,
                reason=f"missed {which} deadline (> {deadline:g}s: "
                       f"hang)")
        return True

    def _bringup_fail_async(self, r: _Replica, now: float,
                            msg: str) -> None:
        """A spawned child that died or stalled before READY: count it
        against the circuit breaker exactly like a synchronous
        constructor failure."""
        c = r.engine
        r.engine, r.queue = None, None
        r.await_ready = False
        if c is not None:
            r.last_exit = c.exit_desc()
            c.fence()               # releases the dead child's pipe
            # routing is gated on ready, so the shadow is normally
            # empty — but never drop a handle on principle
            for h in c.reclaim():
                self.queue.requeue(h)
        r.attempt += 1
        self.bringup_failures += 1
        delay = self.bringup_policy.backoff(min(r.attempt - 1, 20))
        r.next_bringup_t = now + delay
        r.last_error = msg
        r.state = BROKEN
        self._event("serve_replica_bringup_fail", replica=r.index,
                    attempt=r.bringups - 1, consecutive=r.attempt,
                    backoff_s=round(delay, 3), error=msg,
                    exit=r.last_exit)

    def _pump_children(self, now: float) -> bool:
        """Drain every live child's pipe: absorb heartbeats/snapshots,
        fulfil harvested results, notice READY transitions. The one
        place process-mode results enter the parent — called from the
        control loop (threaded) and ``step_once`` (sync drive)."""
        did = False
        for r in self.replicas:
            c = r.engine
            if r.state != RUNNING or c is None:
                continue
            did = c.pump() or did
            if r.await_ready and c.ready:
                r.await_ready = False
                r.attempt = 0
                r.last_error = ""
                r.conns += 1
                self._event("serve_replica_up", replica=r.index,
                            bringups=r.bringups, pid=c.pid,
                            transport=c.transport_kind, peer=c.peer)
                did = True
        return did

    # -- routing ------------------------------------------------------------

    def _expire(self, h: S.RequestHandle, now: float) -> None:
        req = h.request
        self.expired += 1
        self._event("serve_deadline", request_id=req.request_id,
                    where="queued", deadline_s=req.deadline_s,
                    waited_s=round(now - req.submit_t, 4))
        h.fulfill(S.Result(
            status=S.DEADLINE_EXCEEDED, request_id=req.request_id,
            reason=f"deadline_s={req.deadline_s:g} exceeded (queued)",
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _capacity(self, r: _Replica) -> int:
        if self.isolation == "process":
            # parent-authoritative: the shadow (routed, unresolved) is
            # the truth; the child's own reports lag a frame. Allow one
            # queued wave beyond the slot pool so the child can prefill
            # its next group while decoding the current one.
            return max(0, 2 * r.engine.num_slots - len(r.engine.shadow))
        return max(0, r.engine.num_slots - r.engine.active_slots()
                   - r.queue.depth())

    def _pick(self, cands: List[_Replica], caps: dict,
              h: S.RequestHandle) -> _Replica:
        """Least-loaded with page-awareness: most free slot capacity
        first; among paged replicas, one whose pool can map the
        request's prompt span NOW beats one that would defer it, and
        free pages break remaining ties."""
        from dalle_pytorch_tpu.serve import kv_pool as KV

        def score(r: _Replica):
            eng = r.engine
            fits, free_pages = True, 0
            if eng.kv == "paged":
                if self.isolation == "process":
                    # last-frame view: pages_free lags one heartbeat
                    # (-1 = no frame yet -> stay optimistic); the
                    # child's own admission gate is the authority
                    free_pages = eng.pages_free
                    buckets, page_size = self._buckets, self._page_size
                    if free_pages < 0:
                        return (True, caps[r.index], 0, -r.index)
                else:
                    free_pages = eng.alloc.free
                    buckets, page_size = eng.buckets, eng.page_size
                try:
                    need = KV.pages_for(
                        S.bucket_for(len(h.request.codes), buckets),
                        page_size)
                    fits = free_pages >= need
                except ValueError:
                    # an over-long prompt buckets nowhere; the engine's
                    # admission turns it into a typed error result
                    fits = True
            return (fits, caps[r.index], free_pages, -r.index)

        return max(cands, key=score)

    def _route(self, now: float) -> bool:
        """Move ready requests from the shared queue into per-replica
        private queues (a hand-off: ``requeue(count=False)`` keeps the
        handle's shared-queue identity and arrival position). Queued
        deadline expiries are reaped here on EVERY sweep — even with
        zero live replicas, a dead entry must get its typed result."""
        live = [r for r in self.replicas
                if r.state == RUNNING and r.engine is not None]
        if self.isolation == "process":
            # routable = READY and believable: not poisoned/crashed and
            # the PID is live RIGHT NOW — never route into a corpse in
            # the window before the next supervision sweep fences it
            live = [r for r in live
                    if r.engine.ready and not r.engine.poisoned
                    and not r.engine.crashed and not r.engine.fenced
                    and r.engine.alive_proc()]
        caps = {r.index: self._capacity(r) for r in live}
        total = sum(caps.values())
        ready, expired = self.queue.pop_ready(total, now)
        for h in expired:
            self._expire(h, now)
        assigned: dict = {}
        for h in ready:
            cands = [r for r in live if caps[r.index] > 0]
            r = self._pick(cands, caps, h)
            caps[r.index] -= 1
            if self.isolation == "process":
                assigned.setdefault(r.index, (r, []))[1].append(h)
            else:
                r.queue.requeue(h, count=False)
        for r, batch in assigned.values():
            r.engine.route(batch)       # one admit frame per replica
        return bool(ready or expired)

    # -- the replica loop (threaded mode) -----------------------------------

    def _spawn(self, r: _Replica) -> None:
        r.thread = threading.Thread(
            target=self._run_replica, args=(r, r.engine, r.stop),
            daemon=True, name=f"serve-replica-{r.index}")
        r.thread.start()

    def _run_replica(self, r: _Replica, engine, stop) -> None:
        """One replica's serving loop. A step exception is a CRASH —
        recorded for the supervisor, loop exits (contrast the single-
        engine ``Engine.run``, which fails the in-slot requests in
        place: here the supervisor replays them instead, so the callers
        get their exact tokens, not typed errors). A fence (failover
        decided while this thread was wedged) ends the loop on the next
        iteration."""
        from dalle_pytorch_tpu.resilience import faults
        while not stop.is_set() and not engine.fenced:
            try:
                faults.on_replica_chunk(
                    r.index, engine.decode_steps // engine.chunk_steps)
                busy = engine.step_once()
            except Exception as e:  # noqa: BLE001 — supervised crash
                if engine.fenced or r.engine is not engine:
                    # a ZOMBIE crashing: this engine was already fenced
                    # and replaced (e.g. a wedge that finally errored
                    # out) — its requests were reclaimed long ago, and
                    # flagging r.dead now would fail over the healthy
                    # replacement that owns r
                    return
                r.last_error = repr(e)
                r.dead = True
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                return
            if not busy and engine.idle():
                stop.wait(self._idle_sleep_s)

    def _run_control(self, stop: threading.Event) -> None:
        """Routing + supervision loop (threaded mode). In process mode
        this is the ONLY parent-side loop: the children drive their own
        engines, and this thread pumps their pipes, routes, and
        supervises."""
        while not stop.is_set():
            now = self.clock()
            with self._ctl_lock:
                busy = False
                if self.isolation == "process":
                    busy = self._pump_children(now)
                busy = self._check_replicas(now) or busy
                busy = self._route(now) or busy
            stop.wait(0.0005 if busy else self._idle_sleep_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaSet":
        """Threaded mode: one loop thread per live replica plus the
        control thread (routing + supervision)."""
        self._started = True
        if self._t_start is None:       # threaded mode never steps
            self._t_start = self.clock()  # sync, so stamp elapsed here
        if self.isolation != "process":  # children ARE the loops
            for r in self.replicas:
                if r.state == RUNNING and r.thread is None:
                    self._spawn(r)
        self._ctl_stop = threading.Event()
        self._ctl_thread = threading.Thread(
            target=self._run_control, args=(self._ctl_stop,),
            daemon=True, name="serve-replica-control")
        self._ctl_thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Stop supervision, then every replica loop, joining each with
        its share of the deadline. A replica that OUTLIVES its join
        (wedged in a step) is fenced so it can never fulfil or requeue
        later; either way its private queue is drained and every
        still-open handle — queued or in-slot — is fulfilled
        ``cancelled`` lock-free (first-write-wins makes the late-waker
        race harmless). Callers are never stranded."""
        t0 = time.perf_counter()
        self._ctl_stop.set()
        if self._ctl_thread is not None:
            self._ctl_thread.join(timeout)
        if self.isolation == "process":
            with self._ctl_lock:
                for r in self.replicas:
                    c = r.engine
                    if c is None:
                        continue
                    left = max(0.5, timeout - (time.perf_counter() - t0))
                    # graceful SHUTDOWN -> join -> SIGKILL straggler;
                    # close() salvages the pipe and fences, so a child
                    # outliving its join can never fulfil anything late
                    c.close(left / max(self.n_replicas, 1))
                    for h in c.reclaim():
                        h.fulfill(S.Result(
                            status=S.CANCELLED,
                            request_id=h.request.request_id,
                            reason="server shutdown"))
                if self.listener is not None:
                    self.listener.close()
            return
        with self._ctl_lock:
            for r in self.replicas:
                if r.stop is not None:
                    r.stop.set()
            for r in self.replicas:
                if r.thread is not None:
                    left = max(0.1, timeout - (time.perf_counter() - t0))
                    r.thread.join(left / max(len(self.replicas), 1))
            for r in self.replicas:
                eng, q = r.engine, r.queue
                if r.thread is not None and r.thread.is_alive() \
                        and eng is not None:
                    eng.fence()
                handles = []
                if q is not None:
                    handles.extend(q.drain())
                if eng is not None:
                    handles.extend(eng.inflight_handles())
                for h in handles:
                    if not h.done():
                        h.fulfill(S.Result(
                            status=S.CANCELLED,
                            request_id=h.request.request_id,
                            reason="server shutdown"))

    # -- single-threaded drive (tests, bench) -------------------------------

    def step_once(self) -> bool:
        """One set iteration: supervise (bring-ups, crash cleanup),
        route, then step every live replica once. Crashes fail over
        INLINE — the same fence/reclaim/replay path the threaded
        supervisor takes, just synchronously."""
        from dalle_pytorch_tpu.resilience import faults
        now = self.clock()
        if self._t_start is None:
            self._t_start = now
        with self._ctl_lock:
            did = False
            if self.isolation == "process":
                did = self._pump_children(now)
            did = self._check_replicas(now) or did
            did = self._route(now) or did
        if self.isolation == "process":
            # the children step themselves; the parent's "step" is the
            # pump/supervise/route above. Nap briefly when nothing
            # moved so run_until_idle doesn't hot-spin while children
            # decode at their own pace.
            if not did:
                time.sleep(0.001)
            return did
        for r in list(self.replicas):
            if r.state != RUNNING or r.engine is None:
                continue
            eng = r.engine
            try:
                faults.on_replica_chunk(
                    r.index, eng.decode_steps // eng.chunk_steps)
                did = eng.step_once() or did
            except Exception as e:  # noqa: BLE001 — supervised crash
                r.last_error = repr(e)
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                with self._ctl_lock:
                    self._failover(r, self.clock(),
                                   reason=f"crash: {e!r}")
                did = True
        return did

    def idle(self) -> bool:
        if self.queue.depth() > 0:
            return False
        if self.isolation == "process":
            # the shadow is the parent-side truth: anything routed and
            # unresolved is still in flight somewhere
            return all(not r.engine.shadow for r in self.replicas
                       if r.engine is not None)
        for r in self.replicas:
            if r.queue is not None and r.queue.depth() > 0:
                return False
            if r.engine is not None and (r.engine.active_slots() > 0
                                         or r.engine._pending):
                return False
        return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            busy = self.step_once()
            if not busy and self.idle():
                return
        raise RuntimeError(
            f"replica set did not go idle in {max_steps} steps")

    # -- aggregate counters (bench._serve_load_point's surface) -------------

    def _agg(self, name: str) -> int:
        return self._retired[name] + sum(
            getattr(r.engine, name, 0) for r in self.replicas
            if r.engine is not None)

    @property
    def tokens_decoded(self) -> int:
        return self._agg("tokens_decoded")

    @property
    def decode_steps(self) -> int:
        return self._agg("decode_steps")

    @property
    def harvests(self) -> int:
        return self._agg("harvests")

    @property
    def occupancy_sum(self) -> int:
        return self._agg("occupancy_sum")

    @property
    def completed(self) -> int:
        return self._agg("completed")

    # -- observability ------------------------------------------------------

    def alive(self) -> bool:
        """True while at least one replica serves (healthz contract:
        503 only when ALL are dead)."""
        for r in self.replicas:
            if r.state != RUNNING or r.engine is None:
                continue
            if self.isolation == "process":
                if r.engine.alive_proc():
                    return True
            elif r.thread is None or r.thread.is_alive():
                return True
        return False

    def replica_states(self) -> List[dict]:
        """Per-replica /healthz body. Process mode adds the supervised-
        child facts an operator triages with: the child PID, its
        restart count, the decoded last exit (signal name / OOM exit
        137 / plain code), and the child's reported RSS."""
        now = self.clock()
        out = []
        for r in self.replicas:
            if self.isolation == "process":
                alive = r.state == RUNNING and r.engine is not None \
                    and r.engine.alive_proc()
            else:
                alive = r.state == RUNNING and r.engine is not None and \
                    (r.thread is None or r.thread.is_alive())
            rec = {"replica": r.index, "state": r.state, "alive": alive,
                   "bringups": r.bringups}
            if r.engine is not None:
                rec["heartbeat_age_s"] = round(
                    max(now - r.engine.last_heartbeat, 0.0), 4)
            if self.isolation == "process":
                rec["restarts"] = max(r.bringups - 1, 0)
                rec["reconnects"] = max(r.conns - 1, 0)
                if r.engine is not None:
                    rec["pid"] = r.engine.pid
                    rec["rss_mb"] = r.engine.rss_mb
                    rec["ready"] = r.engine.ready
                    rec.update(r.engine.transport_info(now))
                if r.last_exit:
                    rec["last_exit"] = r.last_exit
            if r.last_error:
                rec["last_error"] = r.last_error
            out.append(rec)
        return out

    def decode_compiles_per_replica(self) -> List[int]:
        """Each LIVE replica's decode-program trace count — the
        one-compile-per-replica contract bench_serve asserts (a
        replaced engine is a fresh program, counted on its own)."""
        return [r.engine.decode_traces for r in self.replicas
                if r.engine is not None]

    def _kv_bytes_per_shard(self) -> int:
        """Per-shard KV residency — where one device of a replica's
        slice actually holds the pool (/stats mesh satellite). Read off
        a live thread-mode engine; MODELED from config for child-process
        engines, whose pools live in other interpreters."""
        if self.isolation != "process":
            for r in self.replicas:
                if r.engine is not None:
                    return r.engine._mesh_stats()[
                        "kv_hbm_bytes_per_shard"]
        from dalle_pytorch_tpu.serve import kv_pool as KV
        kw = self._engine_kwargs
        try:
            dtype_bytes = self.params["text_emb"]["w"].dtype.itemsize
        except (TypeError, KeyError, AttributeError):
            dtype_bytes = 4     # worker_ckpt mode may carry no params
        total = KV.modeled_kv_bytes(
            self.cfg.transformer, kv=self.kv,
            num_slots=kw["num_slots"], total_len=self.cfg.seq_len,
            page_size=kw["page_size"], num_pages=kw["num_pages"],
            quantized=kw["quantize_cache"], dtype_bytes=dtype_bytes)
        from dalle_pytorch_tpu.parallel.serve_specs import kv_heads_shard
        m = self.devices_per_replica
        if m > 1 and kv_heads_shard(self.cfg.transformer.heads, m):
            return total // m   # heads-sharded pool divides exactly
        return total

    def stats(self) -> dict:
        # lazy (the serve package's jax-free-import discipline):
        # serve_specs pulls jax, and by stats() time a backend exists
        from dalle_pytorch_tpu.parallel.serve_specs import \
            SERVE_AXIS as _SERVE_AXIS
        elapsed = None if self._t_start is None \
            else max(self.clock() - self._t_start, 1e-9)
        live = [r for r in self.replicas if r.engine is not None]
        proc = self.isolation == "process"
        per = []
        for r in self.replicas:
            rec = {"replica": r.index, "state": r.state}
            if r.engine is not None:
                e = r.engine
                rec.update({
                    "active_slots": e.active_slots(),
                    # routed-but-not-decoding: the shadow holds EVERY
                    # outstanding request (in-slot ones included), so
                    # subtract the active count rather than adding the
                    # child's own queue depth on top — same meaning as
                    # thread mode's private-queue depth
                    "queued": (max(len(e.shadow) - e.active_slots(), 0)
                               if proc
                               else (r.queue.depth() if r.queue else 0)),
                    "decode_compiles": e.decode_traces,
                    "prefill_compiles": e.prefill_traces,
                    "completed": e.completed,
                    "tokens_decoded": e.tokens_decoded,
                })
                if proc:
                    rec.update({"pid": e.pid, "rss_mb": e.rss_mb,
                                "restarts": max(r.bringups - 1, 0),
                                "reconnects": max(r.conns - 1, 0)})
                    rec.update(e.transport_info())
                    if r.last_exit:
                        rec["last_exit"] = r.last_exit
                    if e.kv == "paged" and e.pages_free >= 0:
                        rec["pages_free"] = e.pages_free
                elif e.kv == "paged":
                    rec["pages_free"] = e.alloc.free
            per.append(rec)
        tokens = self.tokens_decoded
        steps = self.decode_steps
        out = {
            "replicas": self.n_replicas,
            "isolation": self.isolation,
            # mesh observability (/stats satellite): how many devices
            # each replica's engine spans, and the mesh shape when > 1
            "devices_per_replica": self.devices_per_replica,
            "mesh_shape": (
                {_SERVE_AXIS: self.devices_per_replica}
                if self.devices_per_replica > 1 else None),
            "kv_hbm_bytes_per_shard": self._kv_bytes_per_shard(),
            "alive_replicas": sum(
                1 for r in self.replicas
                if r.state == RUNNING and r.engine is not None),
            "kv": self.kv,
            "queue_depth": self.queue.depth() + sum(
                r.queue.depth() for r in live if r.queue is not None),
            "num_slots": sum(r.engine.num_slots for r in live),
            "active_slots": sum(r.engine.active_slots() for r in live),
            "chunk_steps": self._engine_kwargs["chunk_steps"],
            "decode_steps": steps,
            "tokens_decoded": tokens,
            "tokens_per_s": (round(tokens / elapsed, 2)
                             if elapsed else 0.0),
            "mean_occupancy": round(self.occupancy_sum / max(steps, 1),
                                    3),
            "completed": self.completed,
            "expired": self._agg("expired") + self.expired,
            "rejected": self.queue.rejected,
            "requeued": self.queue.requeued,
            "decode_compiles": self._agg("decode_traces"),
            "prefill_compiles": self._agg("prefill_traces"),
            "harvests": self.harvests,
            "host_round_trips_per_token": round(
                self.harvests / max(tokens, 1), 6),
            "failovers": self.failovers,
            "reclaimed": self.reclaimed,
            "bringup_failures": self.bringup_failures,
            "evicted": self._agg("evicted"),
            "per_replica": per,
        }
        if proc:
            out["transport"] = self.transport
            if self.listener is not None:
                # where a remote worker dials in, and how many dialers
                # the HELLO gate turned away
                out["worker_endpoint"] = self.listener.endpoint
                out["attach_rejected"] = self.listener.rejected
        return out
