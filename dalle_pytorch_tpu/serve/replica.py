"""Replica-set serving: N supervised engines behind ONE queue, with
zero-loss failover via deterministic replay.

One ``Engine`` is one replica: one compiled decode program over one slot
pool on (ideally) one chip. This module is the layer the ROADMAP's
multi-replica item asks for — a single shared ``RequestQueue`` fronting N
engines (thread-per-engine; the Gemma-on-TPU serving paper's replicated-
engine + health-driven-routing shape, PAPERS.md), where a replica
crashing, hanging, or being drained by an operator costs LATENCY on the
requests it held, never a lost request and never a wrong token.

The key enabler is the same one paged eviction proved (PR 5): sampling
is deterministic in (seed, position) — ``fold_in(request_rng, pos)`` per
step — so an in-flight request is *migratable*. Kill the replica mid-
stream, re-queue the handle at its ORIGINAL arrival position
(``RequestQueue.requeue`` preserves ``queue_seq``), admit it on a
survivor, and the replay emits a token stream bit-identical to an
undisturbed run. The caller cannot tell a failover happened except by
the clock.

Supervision (one supervisor per set, not per request):

  * every replica's serving loop stamps ``Engine.last_heartbeat`` at
    each step and each emit-ring harvest — the harvest ``device_get``
    is the one blocking sync in steady state, so a wedged device stalls
    the stamp exactly where the wedge is;
  * CRASH: the replica loop catches the exception, records it, and
    exits; the supervisor notices the dead loop.
    HANG: ``now - last_heartbeat > heartbeat_s`` while the loop thread
    is still "running". Either way the replica is FENCED
    (``Engine.fence()`` — a fenced engine never fulfils a handle, hands
    a completion downstream, or re-queues anything; the wedged thread
    is abandoned, daemon-style, the same move ``resilience.retry``
    makes for an uncancellable pending claim);
  * RECLAIM: the supervisor snapshots the fenced replica's host-side
    bookkeeping — its private queue (routed, not yet admitted) and its
    in-slot handles (``Engine.inflight_handles``) — and re-queues every
    not-yet-done handle into the shared queue at its original arrival
    position for replay. ``RequestHandle.fulfill`` is first-write-wins,
    so even a fenced thread waking at the worst moment cannot race the
    replay with a stale result;
  * BRING-UP: the replica is rebuilt (fresh ``Engine``, fresh private
    queue). Repeated bring-up failure circuit-breaks the replica with
    exponential backoff (``resilience.retry.RetryPolicy.backoff``)
    while the set keeps serving on the survivors — capacity shrinks,
    the shared queue's ``max_depth`` turns the shrinkage into typed
    ``QueueFull`` backpressure at submit, and nothing ever hangs;
  * DRAIN: ``drain_replica(i)`` is the operator's planned-maintenance
    path — identical fence + reclaim, but the replica stays down until
    ``undrain_replica(i)``.

Routing is least-loaded with page-awareness: the router moves requests
from the shared queue into per-replica private queues (``requeue`` with
``count=False`` — a hand-off, not backpressure; the handle keeps its
shared-queue ``queue_seq`` and ``request_id``), preferring the replica
with the most free slot capacity and, among paged engines, one whose
page pool can map the request's prompt span NOW (free pages from the
replica's kv-pool stats break ties).

Like ``Engine``, the set is drivable two ways: ``step_once``/
``run_until_idle`` single-threaded (tests, bench — deterministic, and
the whole steady state still holds under ``guards.no_transfers`` with
one decode compile per replica), or ``start()`` for live traffic
(thread per replica + one control thread for routing/supervision, what
``serve.server`` uses). With more than one jax device visible, replica
i's engine is committed to device ``i % len(devices)`` so the replicas'
fused chunks genuinely overlap — on a pod slice that is replica-per-
chip serving; on the CPU fallback it still overlaps the async dispatch.

ISOLATION SHAPES. ``isolation='thread'`` (the default) is the above:
replicas are threads sharing this process — cheap, transfer-guardable,
but a segfault in XLA, a host OOM, or a `kill -9` still takes the whole
set down. ``isolation='process'`` runs each replica's engine in a
SPAWNED CHILD PROCESS (own interpreter, own jax client, pinned to its
device — ``serve/worker.py``) behind the typed IPC layer in
``serve/ipc.py``. The fence/reclaim/replay protocol is identical; what
changes is who holds the truth: the parent keeps a SHADOW of every
handle routed to a child (``ChildEngineClient.shadow``) and reclaims
from that, never from the child — a SIGKILLed process answers nothing.
Supervision gains a second liveness signal: child PID liveness with
exit-code/signal decoding (SIGKILL, SIGSEGV, the exit-137 RSS-watchdog
OOM convention) layered on top of the same missed-heartbeat deadline,
where heartbeats are now frames on the pipe rather than a shared-heap
timestamp. A hard-killed child is fenced exactly like a crash or hang:
its pipe is drained for frames written before death (those results
stand), everything still open replays byte-identically on a survivor,
and the dead replica restarts through the same circuit-breaker backoff.

TRANSPORT SHAPES (process isolation only). ``transport='pipe'`` (the
default) carries the frames over a duplex pipe — local children only.
``transport='socket'`` makes isolation HOST-shaped: the parent opens
one dial-in endpoint (``serve/transport.py``'s ``WorkerListener``;
``worker_endpoint`` picks the bind address) and every worker CONNECTS
BACK with an authenticated HELLO (shared token + protocol version +
replica index), then receives its engine spec over the socket. Three
ways a worker comes to exist — a locally spawned child that dials back
(the default), a launcher command per replica (``worker_cmd`` with
``{endpoint}``/``{index}`` placeholders, e.g. an ssh line; the token
travels in the ``DALLE_WORKER_TOKEN`` env var), or a worker an
operator starts BY HAND on another host (``worker_cmd=''``) — and all
three are supervised identically: shadow bookkeeping, heartbeat
deadline, fence→reclaim→replay at original arrival position. A worker
with no local PID is declared dead off its socket (EOF/reset), and the
frame protocol's per-connection sequence numbers + the transport's
torn-frame detection turn every network failure mode — reset
mid-frame, partial frame, stalled link, duplicated or reordered
delivery — into the same typed fence + byte-identical replay a local
`kill -9` gets (docs/SERVING.md 'Host isolation & socket transport').

ELASTIC FLEET. The set is a MOVING TARGET at runtime (docs/SERVING.md
'Elastic fleet'): ``add_replica()`` appends a new supervised slot —
thread, spawned child, launcher-started or hand-started remote worker,
whichever shape the set already runs — that joins routing atomically
once serving (process children once READY); ``remove_replica(i)``
drains in-flight work to the survivors (the same fence→reclaim→replay
that makes failover zero-loss) and RETIRES the slot for good. Illegal
transitions are typed ``ScaleError``\\ s, never partial states: removing
the last live replica, growing past ``max_replicas`` (the page-budget
cap — every replica allocates its own KV pool), reshaping mid-upgrade.
``rolling_upgrade(version=...)`` hot-swaps weights replica-by-replica
with zero dropped requests: drain (in-flight work replays on survivors
still serving the OLD weights), re-bring-up on the new weights (new
params pytree, or a new ``worker_ckpt`` path for checkpoint-path
attach), health-gate behind N CANARY requests decoded by the new engine
alone — token-exact against the first upgraded replica's canary tokens,
so every replica of a generation provably samples identical streams —
and only then rejoin routing. A canary or bring-up failure ABORTS the
upgrade typed (``UpgradeAborted``): the replica rolls back to the old
weights and the whole fleet keeps serving the old version. Every
``Result`` is stamped with the ``weights_version`` that decoded it, and
failover replay is VERSION-PINNED: a request reclaimed mid-upgrade
replays only on a replica of the generation it started on (same-seed
tokens are byte-identical PER version; a newer generation's logits are
not) — the pin is released, with a structured event, only when that
generation has left the fleet entirely, because zero-loss outranks a
stale pin. ``serve/autoscale.py``'s policy loop drives the same two
scale calls off /stats occupancy, queue depth, and page pressure.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional

from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve.engine import COUNTERS as _COUNTERS
from dalle_pytorch_tpu.serve.engine import MigrationError

# replica lifecycle states (``replica_states()`` / ``stats()``)
RUNNING = "running"
BROKEN = "broken"        # circuit open: waiting out the bring-up backoff
DRAINED = "drained"      # operator drain: down until undrain_replica()
RETIRED = "retired"      # scale-in tombstone: the slot never comes back
#                          (indices stay stable; routing, supervision,
#                          and capacity all skip it forever)

ISOLATION_MODES = ("thread", "process")
TRANSPORT_MODES = ("pipe", "socket")
# replica roles (disaggregated serving): a ``prefill`` replica admits
# and prefills, then live-migrates the warm request to a decode-capable
# replica; a ``decode`` replica is skipped for fresh admissions while
# any prefill-capable replica has capacity. ``both`` (the default) is
# the classic undifferentiated shape. Roles are a PREFERENCE, never a
# capability: every engine can prefill and decode, and zero-loss
# routing outranks the role split whenever honoring it would strand a
# request.
REPLICA_ROLES = ("prefill", "decode", "both")


class ScaleError(RuntimeError):
    """Typed rejection of an illegal fleet reshape: removing the last
    live replica, adding past the ``max_replicas`` page-budget cap,
    naming a retired/unknown slot, or scaling while a rolling upgrade
    owns the fleet. ``record`` is the structured event (kind
    ``serve_scale_reject``) — the operator API's machine-readable
    half, mirroring ``scheduler.ServeRejected``."""

    def __init__(self, record: dict):
        super().__init__(f"{record.get('reason', 'scale rejected')} "
                         f"(op={record.get('op')})")
        self.record = record


class UpgradeAborted(RuntimeError):
    """A rolling upgrade that could not complete safely: a canary
    failed the health gate, the new weights' bring-up failed or timed
    out, or the fresh replica died mid-canary. By the time this is
    raised the aborting replica has been rolled back to the OLD
    weights and the whole fleet serves the old version — the abort is
    an event, never a mixed-version end state. ``record`` is the
    structured event (kind ``serve_upgrade_aborted``)."""

    def __init__(self, record: dict):
        super().__init__(
            f"rolling upgrade to {record.get('to')!r} aborted at "
            f"replica {record.get('replica')}: {record.get('error')} "
            f"(fleet left on {record.get('fleet_version')!r})")
        self.record = record


class ReplayVersionMismatch(RuntimeError):
    """Invariant guard on version-pinned replay: a handle pinned to one
    weights generation reached a replica serving another. The router's
    candidate filter makes this unreachable in normal operation (a
    pinned request is HELD in the shared queue until a same-version
    replica has capacity, or the pin is released once the generation
    left the fleet); raising typed here — instead of silently decoding
    on the wrong weights — is what keeps 'byte-identical per
    weights_version' a contract rather than a hope."""

    def __init__(self, record: dict):
        super().__init__(
            f"request {record.get('request_id')} is pinned to weights "
            f"{record.get('pinned')!r} but was offered replica "
            f"{record.get('replica')} on {record.get('version')!r}")
        self.record = record


class _Replica:
    """One supervised slot of the set: the engine + its private queue,
    its loop thread (threaded mode), and the supervisor's bookkeeping
    (lifecycle state, consecutive bring-up failures, backoff clock)."""

    __slots__ = ("index", "state", "engine", "queue", "thread", "stop",
                 "device", "attempt", "bringups", "next_bringup_t",
                 "last_error", "dead", "await_ready", "last_exit",
                 "conns", "version", "canary", "params_override",
                 "ckpt_override", "born_scaled", "role")

    def __init__(self, index: int, device=None, version: str = "0",
                 role: str = "both"):
        self.index = index
        self.state = BROKEN          # until the first bring-up succeeds
        self.engine = None
        self.queue: Optional[S.RequestQueue] = None
        self.thread: Optional[threading.Thread] = None
        self.stop: Optional[threading.Event] = None
        self.device = device
        self.attempt = 0             # consecutive bring-up failures
        self.bringups = 0            # lifetime bring-up calls (faults)
        self.next_bringup_t = 0.0
        self.last_error = ""
        self.dead = False            # loop thread recorded a crash
        self.await_ready = False     # process child spawned, READY due
        self.last_exit = ""          # decoded exit of the last child
        self.conns = 0               # workers that reached READY here
        self.version = str(version)  # weights generation this slot serves
        self.canary = False          # upgrading: serving canaries only,
        #                              excluded from routing until gated
        self.params_override = None  # upgrade: bring up on THESE params
        self.ckpt_override = None    # upgrade: ... or this ckpt path
        self.born_scaled = False     # created by add_replica (faults)
        self.role = str(role)        # prefill | decode | both


class ReplicaSet:
    """N supervised ``Engine`` replicas behind one shared
    ``scheduler.RequestQueue``. Presents the same drive surface as a
    single engine (``step_once`` / ``run_until_idle`` / ``idle`` /
    ``stats`` plus the counters ``bench._serve_load_point`` reads), so
    everything that can drive an engine can drive a set."""

    def __init__(self, params: dict, cfg, queue: S.RequestQueue, *,
                 replicas: int = 2,
                 num_slots: int = 4,
                 chunk_steps: int = 8,
                 prefill_buckets=None,
                 complete: Optional[Callable] = None,
                 metrics=None, log_every: int = 0,
                 quantize_cache: bool = False,
                 kv: str = "dense",
                 page_size: int = 0,
                 num_pages: int = 0,
                 paged_attn: str = "gather",
                 sparse_reads: bool = False,
                 speculative: int = 0,
                 draft_layers: int = 0,
                 prefix_cache: bool = False,
                 preview_every: int = 0,
                 clock: Callable[[], float] = time.perf_counter,
                 heartbeat_s: float = 5.0,
                 bringup_policy=None,
                 place_on_devices: bool = True,
                 idle_sleep_s: float = 0.002,
                 isolation: str = "thread",
                 child_rss_limit_mb: int = 0,
                 spawn_timeout_s: float = 120.0,
                 compile_grace_s: float = 120.0,
                 transport: str = "pipe",
                 worker_endpoint: str = "127.0.0.1:0",
                 worker_cmd: Optional[str] = None,
                 attach_token: Optional[str] = None,
                 worker_ckpt: Optional[str] = None,
                 worker_use_ema: bool = False,
                 worker_quantize: str = "none",
                 devices_per_replica: int = 1,
                 weights_version: str = "0",
                 max_replicas: int = 0,
                 roles=None):
        import jax

        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.resilience import retry as rretry

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.roles = tuple(str(x) for x in roles) if roles else ()
        for role in self.roles:
            if role not in REPLICA_ROLES:
                raise ValueError(f"replica role must be one of "
                                 f"{REPLICA_ROLES}, got {role!r}")
        if self.roles and len(self.roles) != replicas:
            raise ValueError(
                f"roles names {len(self.roles)} replicas but the set "
                f"starts with {replicas}")
        if self.roles and kv != "paged" \
                and any(x != "both" for x in self.roles):
            # disaggregated roles work by LIVE-MIGRATING warm requests
            # from prefill to decode replicas, and migration ships KV
            # pages — a dense cache has no transferable pages
            raise ValueError(
                "prefill/decode replica roles need kv='paged' (the "
                "prefill->decode handoff live-migrates KV pages)")
        self.weights_version = str(weights_version)
        self.max_replicas = int(max_replicas)
        if self.max_replicas and self.max_replicas < replicas:
            raise ValueError(
                f"max_replicas={max_replicas} is below the initial "
                f"replica count {replicas}")
        if isolation not in ISOLATION_MODES:
            raise ValueError(f"isolation must be one of "
                             f"{ISOLATION_MODES}, got {isolation!r}")
        if transport not in TRANSPORT_MODES:
            raise ValueError(f"transport must be one of "
                             f"{TRANSPORT_MODES}, got {transport!r}")
        if transport == "socket" and isolation != "process":
            raise ValueError("transport='socket' requires "
                             "isolation='process' (threads share a "
                             "heap; there is nothing to socket)")
        if worker_cmd is not None and transport != "socket":
            raise ValueError("worker_cmd needs transport='socket' — a "
                             "pipe cannot cross a launcher boundary")
        if worker_ckpt is not None and transport != "socket":
            raise ValueError(
                "worker_ckpt needs transport='socket': its point is "
                "that a worker on ANOTHER host loads weights from its "
                "local checkpoint store instead of receiving pickled "
                "params over the wire")
        self.worker_use_ema = bool(worker_use_ema)
        self.worker_quantize = str(worker_quantize)
        if self.worker_quantize not in ("none", "int8", "int8_kv"):
            raise ValueError(f"worker_quantize must be 'none', 'int8' "
                             f"or 'int8_kv', got {worker_quantize!r}")
        if (self.worker_use_ema or self.worker_quantize != "none") \
                and worker_ckpt is None:
            # these describe the WORKER's local load path; without a
            # ckpt-path spec the parent's (already transformed) params
            # cross the boundary and the flags would silently do
            # nothing — the same misconfiguration hazard as worker_cmd
            raise ValueError(
                "worker_use_ema/worker_quantize transform the "
                "checkpoint a worker loads locally — they need "
                "worker_ckpt (without it, pass params you transformed "
                "yourself)")
        self.devices_per_replica = int(devices_per_replica)
        if self.devices_per_replica < 1:
            raise ValueError(f"devices_per_replica must be >= 1, got "
                             f"{devices_per_replica}")
        if self.devices_per_replica > 1 and paged_attn == "kernel":
            # fail at construction with the typed error, not once per
            # circuit-broken bring-up attempt forever
            from dalle_pytorch_tpu.serve.mesh_engine import \
                MeshPagedAttnError
            from dalle_pytorch_tpu.utils.metrics import structured_event
            raise MeshPagedAttnError(structured_event(
                "serve_mesh_paged_attn_unsupported",
                paged_attn="kernel"))
        # the CLI-harness fault path (DALLE_FAULTS): child plans are cut
        # at spawn time, so the env plan must be live before the first
        # bring-up — no-op when unset or already active
        faults.maybe_activate_from_env()
        self.params = params
        self.cfg = cfg
        self.queue = queue
        self.n_replicas = int(replicas)
        self.complete = complete
        # set-level flight recorder (docs/OBSERVABILITY.md): routing/
        # supervision/scale/upgrade lifecycle events and the router's
        # trace spans, always on — fence events land here WITH the
        # victim replica's own ring embedded, so /debug/events can
        # reconstruct a failover after the victim is gone
        from dalle_pytorch_tpu.obs import flight as oflight
        self.flight = oflight.FlightRecorder(capacity=512)
        self.metrics = oflight.wrap_metrics(self.flight, metrics)
        self.fence_dumps: dict = {}     # replica index -> last dump
        self.clock = clock
        self.heartbeat_s = float(heartbeat_s)
        self.kv = str(kv)
        self.isolation = str(isolation)
        self.transport = str(transport)
        self.worker_cmd = worker_cmd
        self.child_rss_limit_mb = int(child_rss_limit_mb)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.compile_grace_s = float(compile_grace_s)
        self.listener = None
        if self.transport == "socket":
            from dalle_pytorch_tpu.serve import transport as T
            host, port = T.parse_endpoint(worker_endpoint)
            self.listener = T.WorkerListener(
                host, port, token=attach_token,
                on_event=(lambda rec: self._event(rec.pop("kind"),
                                                  **rec)))
        self._engine_kwargs = dict(
            num_slots=num_slots, chunk_steps=chunk_steps,
            prefill_buckets=prefill_buckets, metrics=metrics,
            log_every=log_every, quantize_cache=quantize_cache,
            kv=kv, page_size=page_size, num_pages=num_pages,
            paged_attn=paged_attn, sparse_reads=sparse_reads,
            speculative=speculative, draft_layers=draft_layers,
            prefix_cache=prefix_cache, preview_every=preview_every)
        # progressive-preview hook (serve/stream.py): installed by the
        # server AFTER construction, copied onto each replica engine at
        # bring-up. Thread isolation only — a child-process engine has
        # stand-in handles with no sink, so previews (like streaming)
        # are a typed reject there, and _child_kwargs deliberately
        # omits preview_every.
        self.on_preview: Optional[Callable] = None
        self.worker_ckpt = worker_ckpt
        if self.isolation == "process":
            import numpy as np
            # what crosses the spawn boundary: a host numpy pytree of
            # the params (one device_get here, one upload in the child
            # — the child owns its own device copy), and a picklable
            # subset of the engine kwargs (the metrics sink stays in
            # the parent; supervision events are parent-side). With
            # worker_ckpt set, NO params cross at all: the spec carries
            # the checkpoint path and each worker loads + validates
            # locally (serve/worker.py) — the attach spec shrinks from
            # the full weight pytree to a string
            self._np_params = None if worker_ckpt is not None \
                else jax.tree.map(np.asarray, params)
            self._child_kwargs = dict(
                num_slots=num_slots, chunk_steps=chunk_steps,
                prefill_buckets=prefill_buckets,
                quantize_cache=quantize_cache,
                kv=kv, page_size=page_size, num_pages=num_pages,
                paged_attn=paged_attn, sparse_reads=sparse_reads,
                speculative=speculative, draft_layers=draft_layers,
                prefix_cache=prefix_cache)
            # routing needs page math without an Engine in-process:
            # mirror the engine's bucket/page-size resolution
            self._buckets = (S.prefill_buckets(cfg.text_seq_len)
                             if prefill_buckets is None
                             else tuple(sorted(set(
                                 int(b) for b in prefill_buckets))))
            self._page_size = (int(page_size)
                               or min(16, cfg.seq_len)) if kv == "paged" \
                else 0
        # circuit-breaker backoff between bring-up attempts; serving
        # wants short first retries and a firm cap, not training's
        # minutes-scale defaults
        self.bringup_policy = bringup_policy or rretry.RetryPolicy(
            max_attempts=1, deadline_s=None, base_backoff_s=0.5,
            backoff_multiplier=2.0, max_backoff_s=30.0, jitter=0.0)
        self._idle_sleep_s = float(idle_sleep_s)

        devices = jax.devices()
        self._placed = place_on_devices and len(devices) > 1
        self.replicas: List[_Replica] = []
        for i in range(self.n_replicas):
            self.replicas.append(_Replica(
                i, device=self._device_for(i),
                version=self.weights_version,
                role=self.roles[i] if self.roles else "both"))

        # supervisor counters + retired-engine counter base: a fenced
        # engine's numbers are folded in here at reclaim time (minus the
        # reclaimed requests' harvested prefixes — replay re-credits
        # every token, the same distinct-delivered-tokens discipline as
        # paged eviction), so the set's aggregates survive failovers
        self._retired = {k: 0 for k in _COUNTERS}
        self.failovers = 0
        self.reclaimed = 0
        self.expired = 0             # router-side queued-deadline reaps
        self.bringup_failures = 0
        # elastic-fleet bookkeeping (scale API + rolling upgrade)
        self.scale_outs = 0
        self.scale_ins = 0
        self.upgrades = 0            # completed rolling upgrades
        self._upgrading = False      # one reshape owner at a time
        # live KV migration (drain/scale-in/upgrade/role handoff):
        # set-level counters — migration is a SET concern (a request
        # moving between engines), so the counters live here rather
        # than in every engine's COUNTERS tuple
        self.migrations = 0
        self.migrate_fallbacks = 0
        self.migrated_tokens_saved = 0
        self.migration_seconds: List[float] = []  # histogram samples
        self._role_sweep_t = 0.0     # prefill->decode handoff pacing
        # set-level HOL page reservations handed back by fenced/drained
        # replicas: {request_id: pages_needed}. The router routes such a
        # request with its EXACT (prefix-aware) need instead of the
        # blind full-span guess, and the reservation clears the moment
        # it lands on a replica (whose own _hol floor takes over).
        self._hol_handoff: dict = {}
        self.hol_handoffs = 0
        # version-pinned replay: rids currently HELD for a same-version
        # replica (event de-dup), and the canary machinery's id space —
        # negative, so canary requests can never collide with the
        # shared queue's monotonically increasing request ids
        self._version_holds: set = set()
        self._canary_ids = itertools.count(-1000, -1)
        self._canary_ref: dict = {}  # (version, k) -> token reference
        self._ctl_lock = threading.Lock()
        self._started = False
        self._ctl_thread: Optional[threading.Thread] = None
        self._ctl_stop = threading.Event()
        self._t_start: Optional[float] = None

        # no other thread exists yet, but _bring_up mutates set-level
        # counters (bringup_failures) that every later call site guards
        # with _ctl_lock — keep the discipline uniform from the start
        with self._ctl_lock:
            now = self.clock()
            for r in self.replicas:
                self._bring_up(r, now)

    # -- events -------------------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self.metrics is not None:
            try:
                self.metrics.event(**S.structured_event(kind, **fields))
            except Exception:   # noqa: BLE001 — observability must never
                pass            # take down supervision

    def _mark_replay(self, h: S.RequestHandle, reason: str,
                     replica: int) -> None:
        """Stamp the failover-replay link on a reclaimed handle's trace:
        the ``replayed_from`` span covers the fence gap (victim's last
        progress -> re-queue) under its own name — the timeline shows a
        labeled gap, never decode time that didn't happen — and opens
        the next attempt. The marker also lands in the set ring."""
        if h.trace is not None:
            self.flight.record(h.trace.replay(
                self.clock(), reason=reason, replica=replica))

    def _scale_error(self, op: str, **fields) -> ScaleError:
        """A typed reshape rejection with the set ring's tail embedded:
        the refusal record carries the recent lifecycle events that
        explain WHY (who is mid-upgrade, which bring-up failed), so the
        operator's 409 body is a diagnosis, not just a verdict."""
        return ScaleError(S.structured_event(
            "serve_scale_reject", op=op, **fields,
            flight=self.flight.tail(32)))

    def debug_events(self) -> dict:
        """The ``GET /debug/events`` body: the set-level ring, every
        live replica's ring (a process replica's parent-side mirror),
        and the last fence dump per fenced replica index."""
        out = {"server": self.flight.dump(), "replicas": {},
               "fenced": {str(i): d for i, d in
                          self.fence_dumps.items()}}
        for r in self.replicas:
            fl = getattr(r.engine, "flight", None)
            if fl is not None:
                out["replicas"][str(r.index)] = fl.dump()
        return out

    def _device_for(self, i: int):
        """Placement for replica ``i`` — shared by the constructor and
        ``add_replica`` (a replica born at runtime places exactly like
        one born at startup)."""
        import jax
        devices = jax.devices()
        if self.devices_per_replica > 1 and self.isolation != "process":
            # replica = mesh SLICE: devices [i*m, (i+1)*m) (wrapped
            # like the single-chip i % n placement when the host
            # holds fewer slices than replicas). A mesh engine is
            # always pinned to its slice — unpinned, every replica
            # would shard over ALL devices and serialize against
            # the others. Process mode resolves the slice in the
            # WORKER from its own jax client (serve/worker.py): a
            # remote worker's devices live on its host, and the
            # parent — possibly a 0-accelerator head node — must
            # not gate construction on holding them locally.
            from dalle_pytorch_tpu.parallel import serve_specs as SS
            return SS.slice_devices(devices, i, self.devices_per_replica)
        return devices[i % len(devices)] if self._placed else None

    def _on_complete(self, handle: S.RequestHandle,
                     result: S.Result) -> None:
        """Every thread-mode engine's ``complete`` hook: canary handles
        (rolling upgrade's health-gate probes) are fulfilled directly —
        they must never reach the server's postprocess stage or latency
        accounting — everything else flows to the set's downstream
        ``complete`` exactly as before."""
        if getattr(handle, "canary", False) or self.complete is None:
            handle.fulfill(result)
        else:
            self.complete(handle, result)

    # -- bring-up / circuit breaker -----------------------------------------

    def _bring_up(self, r: _Replica, now: float) -> bool:
        """One bring-up attempt: fresh private queue + fresh Engine (the
        old pair, if any, was fenced and drained at reclaim — reusing
        the drained queue would cancel the NEW engine's evictions).
        Failure schedules the next attempt with exponential backoff;
        the replica stays circuit-broken (BROKEN) in between."""
        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.serve.engine import Engine

        attempt = r.bringups
        r.bringups += 1
        # per-replica weight resolution: a replica mid-upgrade carries
        # an override (new params pytree, or a new ckpt path for the
        # checkpoint-path attach shape); everyone else serves the
        # set-level weights. weights_version rides into the engine so
        # every Result it fulfils is stamped with the generation that
        # decoded it — and the same string keys the prefix cache
        # (model_version), so an upgraded replica can never serve a
        # previous generation's cached prompt KV.
        params = self.params if r.params_override is None \
            else r.params_override
        ckpt = self.worker_ckpt if r.ckpt_override is None \
            else r.ckpt_override
        versioned = dict(weights_version=r.version,
                         model_version=r.version)
        try:
            faults.on_replica_bringup(r.index, attempt)
            if r.born_scaled:
                # the scale-out fault row: a replica born from
                # add_replica killed mid-bring-up (circuit-breaks and
                # retries; the serving survivors must be untouched)
                faults.on_scale_add_bringup(r.index, attempt)
            if self.isolation == "process":
                from dalle_pytorch_tpu.serve import ipc
                if ckpt is not None:
                    np_params = None
                elif r.params_override is not None:
                    import jax
                    import numpy as np
                    np_params = jax.tree.map(np.asarray,
                                             r.params_override)
                else:
                    np_params = self._np_params
                client = ipc.ChildEngineClient(
                    np_params, self.cfg,
                    index=r.index,
                    engine_kwargs={**self._child_kwargs, **versioned},
                    device_index=r.index,
                    place=self._placed,
                    devices_per_replica=self.devices_per_replica,
                    ckpt_path=ckpt,
                    ckpt_use_ema=self.worker_use_ema,
                    ckpt_quantize=self.worker_quantize,
                    heartbeat_interval_s=min(
                        max(self.heartbeat_s / 5, 0.01), 0.25),
                    rss_limit_mb=self.child_rss_limit_mb,
                    # hard-fault plans cross the boundary ONCE per
                    # activation per replica (fire-once must outlive
                    # the child — see faults.child_plan_for)
                    fault_plan=faults.child_plan_for(r.index),
                    idle_sleep_s=self._idle_sleep_s,
                    clock=self.clock,
                    on_done=self._child_done,
                    transport=self.transport,
                    listener=self.listener,
                    worker_cmd=self.worker_cmd)
            else:
                queue = S.RequestQueue(
                    max_depth=4 * self._engine_kwargs["num_slots"] + 8,
                    clock=self.clock)
                if self.devices_per_replica > 1:
                    # replica = mesh slice: same Engine surface, params
                    # + KV sharded over this replica's device slice —
                    # which is why nothing else in this module changes
                    from dalle_pytorch_tpu.serve.mesh_engine import \
                        MeshEngine
                    engine = MeshEngine(
                        params, self.cfg, queue,
                        complete=self._on_complete, clock=self.clock,
                        devices=r.device,
                        **{**self._engine_kwargs, **versioned})
                else:
                    engine = Engine(params, self.cfg, queue,
                                    complete=self._on_complete,
                                    clock=self.clock,
                                    device=r.device,
                                    **{**self._engine_kwargs,
                                       **versioned})
                # every bring-up (initial, restart, scale-out) inherits
                # the set-level preview hook — a replica that replaced
                # a crashed one keeps streaming previews
                engine.on_preview = self.on_preview
        except Exception as e:  # noqa: BLE001 — circuit-break, don't die
            r.attempt += 1
            self.bringup_failures += 1
            delay = self.bringup_policy.backoff(min(r.attempt - 1, 20))
            r.next_bringup_t = now + delay
            r.last_error = repr(e)
            r.state = BROKEN
            self._event("serve_replica_bringup_fail", replica=r.index,
                        attempt=attempt, consecutive=r.attempt,
                        backoff_s=round(delay, 3), error=repr(e))
            return False
        if self.isolation == "process":
            # the spawn is async: the child is importing jax and
            # building its engine. RUNNING means "spawned"; routing is
            # gated on client.ready, and _check_replicas turns a child
            # that dies or stalls before READY into a bring-up failure
            # (with backoff), not a failover — there is nothing to
            # reclaim yet. r.attempt resets when READY lands.
            r.engine, r.queue = client, None
            r.dead = False
            r.await_ready = True
            r.stop = None
            r.state = RUNNING
            return True
        # an orphan is a handle the fenced engine popped but never
        # admitted (fence landed mid-step): back to the shared queue
        engine.on_fenced_orphan = \
            lambda h: self.queue.requeue(h)
        r.engine, r.queue = engine, queue
        r.attempt = 0
        r.dead = False
        r.last_error = ""
        r.stop = threading.Event()
        r.state = RUNNING
        self._event("serve_replica_up", replica=r.index,
                    bringups=r.bringups, device=str(r.device))
        if self._started:
            self._spawn(r)
        return True

    def _child_done(self, handle: S.RequestHandle,
                    result: S.Result) -> None:
        """Completion hand-off for process-mode results (the client's
        ``on_done``): same contract as ``Engine._finish`` — OK results
        flow downstream (postprocess), everything else fulfils the
        handle directly. Canary probes (rolling upgrade) never flow
        downstream: the health gate reads them, nobody else."""
        if result.status == S.OK and self.complete is not None \
                and not getattr(handle, "canary", False):
            self.complete(handle, result)
        else:
            handle.fulfill(result)

    # -- fencing and reclaim (failover / drain) -----------------------------

    def _fence_and_reclaim(self, r: _Replica, now: float,
                           reason: str) -> int:
        """Fence the replica's engine, then reclaim every request it
        held — private queue first (routed, never admitted), then the
        in-slot handles — back into the shared queue at their original
        arrival positions for deterministic replay. Fencing comes FIRST:
        from that point the old engine cannot fulfil, complete, or
        requeue anything, so the reclaim sweep is the single owner of
        these handles (a wedge waking later hits the fence, and
        ``fulfill`` being first-write-wins closes the last window).

        Process mode inverts one step on purpose: the child is KILLED
        first (SIGKILL — crashed, wedged, or lying, all three deserve
        -9), then the pipe is drained for frames written before death
        (salvaged results stand and are NOT replayed; the final
        snapshot is the last consistent counter state), and only then
        is the client fenced and the shadow reclaimed. Killing before
        salvaging is what makes the drain safe: a dead writer cannot
        extend the stream while we read it."""
        if self.isolation == "process":
            return self._fence_and_reclaim_child(r, now, reason)
        eng, q = r.engine, r.queue
        r.engine, r.queue, r.thread = None, None, None
        if r.stop is not None:
            r.stop.set()
        reclaimed = 0
        if eng is not None:
            eng.fence()
            # a crashed/exited loop left the lock free and the hang
            # fault sleeps outside it, so this normally succeeds; a
            # thread truly wedged INSIDE a step keeps the lock — the
            # snapshot below is host-side bookkeeping only, safe to
            # read anyway, and the fence already disarmed the wedge
            got = eng._lock.acquire(timeout=0.2)
            try:
                queued = q.drain() if q is not None else []
                slots = [s for s in list(eng.slots) if s is not None]
                # inflight covers the slots AND any mid-admission
                # handles a thread wedged inside the admission compile
                # holds in step locals (engine._admitting)
                inflight = eng.inflight_handles()
                # the engine's head-of-line page reservation must not
                # die with it: hand it back to the shared-queue level
                # (the router routes the waiting request with its EXACT
                # prefix-aware need, not the blind full-span guess)
                hol = (None if eng.kv != "paged"
                       or eng._hol_rid is None
                       else (eng._hol_rid, eng._hol_need))
            finally:
                if got:
                    eng._lock.release()
            # fold the dead engine's counters into the set's base,
            # un-crediting reclaimed requests' harvested prefixes: the
            # replay re-credits every token, and the aggregate must
            # keep counting DISTINCT delivered tokens (same discipline
            # as paged eviction's un-credit)
            retire = {k: getattr(eng, k, 0) for k in _COUNTERS}
            for s in slots:
                retire["tokens_decoded"] -= len(s.emitted)
                retire["occupancy_sum"] -= len(s.emitted)
            for k in _COUNTERS:
                self._retired[k] += retire[k]
            seen: set = set()
            for h in queued + inflight:
                rid = h.request.request_id
                if h.done() or rid in seen:
                    continue
                seen.add(rid)
                if getattr(h, "canary", False):
                    # an upgrade probe dying with its replica: cancel,
                    # never replay — a canary in the shared queue would
                    # decode as (and be billed like) real traffic
                    h.fulfill(S.Result(
                        status=S.CANCELLED, request_id=rid,
                        reason="canary cancelled (replica fenced)"))
                    continue
                # original arrival position: zero-loss AND no
                # queue-jumping — a replayed request neither loses
                # its place nor steals anyone else's
                self._mark_replay(h, reason, r.index)
                self.queue.requeue(h)
                reclaimed += 1
            if hol is not None and hol[0] in seen:
                self._hol_handoff[hol[0]] = hol[1]
                self.hol_handoffs += 1
                self._event("serve_hol_handoff", replica=r.index,
                            request_id=hol[0], pages_needed=hol[1])
        # the victim's flight recorder rides the fence event: the ring
        # was always on, so the post-mortem exists even when no JSONL
        # sink was ever configured
        dump = eng.flight.dump() if eng is not None \
            and getattr(eng, "flight", None) is not None else []
        self.fence_dumps[r.index] = dump
        self.reclaimed += reclaimed
        self._event("serve_replica_fenced", replica=r.index,
                    reason=reason, reclaimed=reclaimed, flight=dump)
        return reclaimed

    def _fence_and_reclaim_child(self, r: _Replica, now: float,
                                 reason: str) -> int:
        """The process-mode half of ``_fence_and_reclaim`` (see its
        docstring): kill -> salvage -> fence -> reclaim-from-shadow."""
        client = r.engine
        r.engine, r.queue, r.thread = None, None, None
        r.await_ready = False
        reclaimed = 0
        if client is not None:
            # how the child died, honestly: a child that was already
            # dead when we got here died on its own (signal/OOM/crash
            # — the decoded exit is the story); a child WE are killing
            # (drain, hang, protocol error) must not advertise
            # 'killed by SIGKILL' as if the OS had done it
            died_on_its_own = not client.alive_proc()
            client.hard_kill()
            r.last_exit = (client.exit_desc() if died_on_its_own
                           else f"hard-killed by supervisor ({reason})")
            client.salvage()
            client.fence()
            handles = client.reclaim()
            retire = client.retire_counters(handles)
            for k in _COUNTERS:
                self._retired[k] += retire.get(k, 0)
            rids = set()
            for h in handles:
                rid = h.request.request_id
                if getattr(h, "canary", False):
                    # same rule as the thread path: probes die with
                    # their replica, they never replay as traffic
                    h.fulfill(S.Result(
                        status=S.CANCELLED, request_id=rid,
                        reason="canary cancelled (replica fenced)"))
                    continue
                rids.add(rid)
                # original arrival position: zero-loss AND no
                # queue-jumping, same as the thread path
                self._mark_replay(h, reason, r.index)
                self.queue.requeue(h)
                reclaimed += 1
            # the child's last-frame HOL reservation (serve/ipc.py
            # snapshots mirror it) hands back exactly like a thread
            # engine's — the corpse can't be asked, the mirror can
            if client.hol is not None and client.hol[0] in rids:
                self._hol_handoff[client.hol[0]] = client.hol[1]
                self.hol_handoffs += 1
                self._event("serve_hol_handoff", replica=r.index,
                            request_id=client.hol[0],
                            pages_needed=client.hol[1])
        # the parent-side MIRROR ring (fed by the frames the child
        # shipped before dying) is what a SIGKILL cannot destroy: the
        # dump is whatever the victim managed to tell us, which the
        # frame protocol guarantees is a consistent prefix
        dump = client.flight.dump() if client is not None \
            and getattr(client, "flight", None) is not None else []
        self.fence_dumps[r.index] = dump
        self.reclaimed += reclaimed
        self._event("serve_replica_fenced", replica=r.index,
                    reason=reason, reclaimed=reclaimed,
                    exit=r.last_exit, flight=dump)
        return reclaimed

    def _failover(self, r: _Replica, now: float, reason: str) -> None:
        self.failovers += 1
        self._fence_and_reclaim(r, now, reason)
        r.state = BROKEN
        r.next_bringup_t = now          # first restart attempt is free;
        #                                 backoff only after it fails

    # -- live KV migration (drain / scale-in / upgrade / roles) -------------

    def _migrate_targets(self, src: _Replica,
                         pin: Optional[str],
                         exclude_prefill: bool = False) -> List[_Replica]:
        """Replicas that could take a migrated request RIGHT NOW:
        serving, not a canary, version-matched when the request is
        pinned, with slot capacity. Decode-capable targets sort first
        (a ``prefill`` replica is a landing spot of last resort — and
        never one at all for the prefill->decode handoff sweep, which
        would otherwise ping-pong work between prefill replicas)."""
        out = []
        for x in self.replicas:
            if x is src or x.state != RUNNING or x.engine is None \
                    or x.canary:
                continue
            if pin is not None and x.version != pin:
                continue
            if exclude_prefill and x.role == "prefill":
                continue
            if not self._replica_serving(x):
                continue
            if self._capacity(x) <= 0:
                continue
            out.append(x)
        out.sort(key=lambda x: (x.role == "prefill",
                                -self._capacity(x), x.index))
        return out

    def _inslot_requests(self, r: _Replica):
        """``(request_id, handle)`` for every request that may hold a
        live slot on ``r`` — exact for a thread engine (read off its
        slot table), the full shadow for a process child (the parent
        cannot see which shadow entries are in-slot; an export of a
        merely-queued one answers ``not_found`` and is skipped).
        Canary probes never migrate — they exist to gate ONE replica."""
        if self.isolation == "process":
            return [(rid, h) for rid, h in list(r.engine.shadow.items())
                    if not h.done() and not getattr(h, "canary", False)]
        eng = r.engine
        out = []
        with eng._lock:
            for s in eng.slots:
                if s is not None and s.shadow_of is None \
                        and not s.handle.done() \
                        and not getattr(s.handle, "canary", False):
                    out.append((s.handle.request.request_id, s.handle))
        return out

    def _migrate_fallback(self, src: _Replica, rid: int,
                          handle: Optional[S.RequestHandle],
                          reason: str, detail: str, now: float) -> None:
        """One migration attempt giving up: structured event + counter,
        and — when the export already VACATED the source slot (handle
        in hand) — the replay fallback itself: requeue at the original
        arrival position, exactly like a fence reclaim. With no handle
        the request never left the source, so the fence that follows a
        failed drain-migration replays it through the normal path."""
        self.migrate_fallbacks += 1
        self._event("serve_migrate_fallback", request_id=rid,
                    replica=src.index, reason=reason, error=detail)
        if handle is not None and not handle.done():
            self._mark_replay(handle, f"migration fallback ({reason})",
                              src.index)
            self.queue.requeue(handle)

    def _migrate_from(self, src: _Replica, now: float, reason: str,
                      pin_version: Optional[str] = None,
                      exclude_prefill: bool = False) -> int:
        """Move ``src``'s in-slot requests to live targets MID-STREAM
        — KV pages, decode cursor, RNG and all — instead of replaying
        them from token zero. The planned-downtime paths (operator
        drain, scale-in, rolling-upgrade drain, autoscaler scale-in)
        call this immediately before their fence; the prefill->decode
        role sweep calls it on a healthy source. Replay stays the
        automatic fallback at every rung: source dead or denies the
        export -> the fence's reclaim replays; export succeeded but no
        target can map it -> requeued for replay right here. Returns
        the number of requests migrated."""
        from dalle_pytorch_tpu.resilience import faults
        if self.kv != "paged" or src.engine is None:
            return 0    # dense KV has no transferable pages
        if not self._replica_serving(src):
            return 0    # a corpse answers nothing: replay handles it
        moved = 0
        for rid, pre in self._inslot_requests(src):
            pin = getattr(pre, "replay_version", None) or pin_version \
                or src.version
            targets = self._migrate_targets(src, pin, exclude_prefill)
            if not targets:
                break   # nowhere to land anything: fence will replay
            t0 = time.perf_counter()
            handle: Optional[S.RequestHandle] = None
            try:
                # the crash-mid-transfer fault row: SIGKILL the source
                # exactly as the snapshot is requested — the export
                # times out against a corpse and everything it held
                # falls back to fence-reclaim replay, zero loss
                faults.on_migrate_transfer(
                    src.index,
                    getattr(src.engine, "pid", None)
                    if self.isolation == "process" else None)
                if self.isolation == "process":
                    snap = src.engine.export_request(rid)
                    handle = src.engine.shadow.pop(rid, None)
                    if handle is None:
                        raise MigrationError(
                            "not_found", "no shadow handle for the "
                            "exported request")
                else:
                    snap, handle = src.engine.export_request(rid)
            except MigrationError as e:
                if e.reason == "not_found":
                    # queued / mid-admission / just completed: nothing
                    # mid-stream to move — not a fallback, the normal
                    # paths own it
                    continue
                self._migrate_fallback(src, rid, handle, e.reason,
                                       str(e), now)
                if not self._replica_serving(src):
                    break   # source died under us: fence replays rest
                continue
            except faults.FaultInjected as e:
                self._migrate_fallback(src, rid, handle, "source_dead",
                                       str(e), now)
                continue
            saved = len(snap.get("emitted") or ())
            dst = None
            err_reason, err_detail = "target_pages", ""
            for tgt in targets:
                try:
                    # the reject-target fault row: the target reports
                    # page exhaustion at import time
                    faults.on_migrate_import(tgt.index)
                    if self.isolation == "process":
                        tgt.engine.import_request(snap, handle)
                    else:
                        tgt.engine.import_slot(snap, handle)
                    dst = tgt
                    break
                except MigrationError as e:
                    err_reason, err_detail = e.reason, str(e)
                except faults.FaultInjected as e:
                    err_reason, err_detail = "target_pages", str(e)
            if dst is None:
                # the export vacated the source slot and credited its
                # prefix to the source's counters; the replay re-decodes
                # and re-credits every token, so un-credit here to keep
                # the aggregate counting DISTINCT delivered tokens (the
                # same discipline as eviction and fence reclaim)
                self._retired["tokens_decoded"] -= saved
                self._retired["occupancy_sum"] -= saved
                self._migrate_fallback(src, rid, handle, err_reason,
                                       err_detail, now)
                continue
            wall = time.perf_counter() - t0
            moved += 1
            self.migrations += 1
            self.migrated_tokens_saved += saved
            self.migration_seconds.append(wall)
            if handle.trace is not None:
                self.flight.record(handle.trace.span(
                    "migrate", now, src=src.index, dst=dst.index,
                    tokens_saved=saved))
            self._event("serve_migrated", request_id=rid,
                        src=src.index, dst=dst.index,
                        tokens_saved=saved, reason=reason,
                        wall_s=round(wall, 4))
        return moved

    def _role_handoff(self, now: float) -> bool:
        """The disaggregated-serving sweep: a ``prefill`` replica keeps
        admission + prefill and hands every warm (in-slot, decoding)
        request to a decode-capable replica the moment one has
        capacity. Paced — the sweep costs an export probe per in-slot
        request, so it runs at most every 50ms, and not at all in a
        homogeneous (all-``both``) fleet."""
        if self.kv != "paged" or self._upgrading:
            return False
        sources = [r for r in self.replicas
                   if r.state == RUNNING and r.role == "prefill"
                   and r.engine is not None]
        if not sources or now - self._role_sweep_t < 0.05:
            return False
        self._role_sweep_t = now
        did = False
        for r in sources:
            did = bool(self._migrate_from(
                r, now, reason="prefill_handoff",
                exclude_prefill=True)) or did
        return did

    # -- operator drain -----------------------------------------------------

    def drain_replica(self, index: int,
                      reason: str = "operator drain") -> int:
        """Planned maintenance: live-migrate the in-flight work to
        survivors mid-stream (each moved request keeps every token it
        already decoded), then fence + reclaim whatever could not move
        (replays on the survivors — zero requests lost either way) and
        hold the replica DOWN until ``undrain_replica``. Returns the
        number of requests handed to survivors (migrated + reclaimed)."""
        with self._ctl_lock:
            self._reject_mid_upgrade("drain")
            r = self._replica_or_reject("drain", index)
            now = self.clock()
            # racelint: disable=RL003 — deliberate: reshapes are
            # serialized by _ctl_lock end-to-end; migration transfers
            # (and the fault hooks that delay them in tests) run under
            # it so no second reshape can observe a half-moved slot.
            # The data plane (engine/queue locks) is not held here.
            moved = self._migrate_from(r, now, reason=reason)
            n = self._fence_and_reclaim(r, self.clock(), reason)
            r.state = DRAINED
            return moved + n

    def undrain_replica(self, index: int) -> bool:
        """Bring a drained replica back into routing (one bring-up
        attempt now; failure re-enters the circuit-breaker path)."""
        with self._ctl_lock:
            self._reject_mid_upgrade("undrain")
            r = self.replicas[index]
            if r.state != DRAINED:
                return False
            return self._bring_up(r, self.clock())

    # -- elastic fleet: runtime scale-out/in --------------------------------

    def _replica_or_reject(self, op: str, index: int) -> _Replica:
        """The slot an operator named, or a typed ``ScaleError`` — a
        retired tombstone or an out-of-range index must never be acted
        on half-way."""
        if not 0 <= index < len(self.replicas):
            raise self._scale_error(op, replica=index,
                                    reason="no_such_replica",
                                    replicas=len(self.replicas))
        r = self.replicas[index]
        if r.state == RETIRED:
            raise self._scale_error(op, replica=index,
                                    reason="replica_retired")
        return r

    def _reject_mid_upgrade(self, op: str) -> None:
        if self._upgrading:
            raise self._scale_error(op, reason="upgrade_in_progress")

    def add_replica(self, role: str = "both") -> int:
        """Runtime scale-out: append one new supervised slot — same
        isolation/transport/mesh shape as the rest of the set — and
        bring it up now. The replica joins routing ATOMICALLY once
        serving (thread engines immediately; process children at their
        READY frame — ``_route`` never offers work to a slot that
        cannot take it), and a bring-up failure circuit-breaks with
        backoff exactly like a failover restart: the survivors never
        notice. Growing past ``max_replicas`` is a typed ``ScaleError``
        — the cap exists because every replica allocates its own KV
        page pool, so fleet width is an HBM page budget, not a free
        integer. Returns the new replica's index."""
        with self._ctl_lock:
            self._reject_mid_upgrade("add")
            if role not in REPLICA_ROLES:
                raise self._scale_error("add", reason="unknown_role",
                                        role=str(role))
            if role != "both" and self.kv != "paged":
                raise self._scale_error(
                    "add", reason="roles_need_paged_kv", role=role)
            active = [r for r in self.replicas if r.state != RETIRED]
            if self.max_replicas and len(active) >= self.max_replicas:
                raise self._scale_error(
                    "add", reason="scale_out_past_cap",
                    replicas=len(active),
                    max_replicas=self.max_replicas)
            index = len(self.replicas)
            r = _Replica(index, device=self._device_for(index),
                         version=self.weights_version, role=role)
            r.born_scaled = True
            self.replicas.append(r)
            self.n_replicas = len(active) + 1
            self.scale_outs += 1
            self._event("serve_scale_out", replica=index,
                        replicas=self.n_replicas,
                        weights_version=self.weights_version)
            self._bring_up(r, self.clock())
            return index

    def remove_replica(self, index: int, drain: bool = True,
                       reason: str = "operator scale-in") -> int:
        """Runtime scale-in: drain ``index``'s in-flight work to the
        survivors — LIVE-MIGRATED mid-stream first (KV pages + decode
        cursor move; every already-decoded token is kept), with the
        fence→reclaim→replay of failover as the unconditional fallback
        for anything that could not move (zero-loss is not a flag;
        ``drain=False`` skips the migration pass and names the
        operator's replay-only intent in the event stream) — and
        RETIRE the slot for good. Removing the last live replica is a
        typed ``ScaleError``: a set with no slots is not a smaller
        fleet, it is an outage an operator almost certainly didn't
        mean. Returns the number of requests handed to survivors
        (migrated + reclaimed)."""
        with self._ctl_lock:
            self._reject_mid_upgrade("remove")
            r = self._replica_or_reject("remove", index)
            survivors = [x for x in self.replicas
                         if x is not r and x.state != RETIRED]
            if not survivors:
                raise self._scale_error("remove", replica=index,
                                        reason="remove_last_replica")
            now = self.clock()
            # racelint: disable=RL003 — deliberate: scale-in migrates
            # under _ctl_lock so the reshape is atomic against other
            # control-plane ops; the data plane stays unlocked
            moved = self._migrate_from(r, now, reason=reason) \
                if drain else 0
            n = self._fence_and_reclaim(r, self.clock(), reason)
            r.state = RETIRED
            r.params_override = None
            r.ckpt_override = None
            self.n_replicas = len(survivors)
            self.scale_ins += 1
            self._event("serve_scale_in", replica=index, drain=drain,
                        migrated=moved, reclaimed=n,
                        replicas=self.n_replicas)
            return moved + n

    # -- elastic fleet: rolling weight hot-swap -----------------------------

    def _drive_until(self, pred: Callable[[], bool],
                     timeout_s: float) -> bool:
        """Wait for ``pred`` while keeping the set moving: in threaded
        mode the control loop is already running, so just sleep; in
        single-threaded drive (tests, bench) the caller IS the loop,
        so step. Wall-clock bounded either way."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if pred():
                return True
            if self._started:
                time.sleep(0.005)
            else:
                self.step_once()
        return pred()

    def _replica_serving(self, r: _Replica) -> bool:
        """The replica's engine can decode a request RIGHT NOW (for a
        process child: READY landed and the process is believable)."""
        if r.state != RUNNING or r.engine is None:
            return False
        if self.isolation == "process":
            c = r.engine
            return c.ready and not c.crashed and not c.poisoned \
                and not c.fenced and c.alive_proc()
        return True

    def _submit_canaries(self, r: _Replica, version: str,
                         canary_codes, n: int) -> List[S.RequestHandle]:
        """Hand ``n`` canary requests DIRECTLY to replica ``r`` —
        never through the shared queue, where a survivor would answer
        them and the gate would prove nothing. Canary ids are negative
        (they can never collide with queue-assigned request ids) and
        the handles are marked so reclaim cancels rather than replays
        them and completions bypass the postprocess stage."""
        now = self.clock()
        handles = []
        for k in range(n):
            codes = tuple(canary_codes[k % len(canary_codes)])
            rid = next(self._canary_ids)
            req = S.Request(codes=codes, seed=10_000 + k,
                            request_id=rid, submit_t=now)
            h = S.RequestHandle(req)
            h.queue_seq = rid       # unique (negative), heap-safe
            h.canary = True
            h.replay_version = version
            handles.append(h)
        with self._ctl_lock:
            if self.isolation == "process":
                r.engine.route(handles)
            else:
                for h in handles:
                    r.queue.requeue(h, count=False)
        return handles

    def _abort_upgrade(self, r: _Replica, version: str,
                       old_version: str, error: str,
                       timeout_s: float) -> None:
        """Roll the WHOLE fleet back to the old weights and raise the
        typed ``UpgradeAborted`` — the failing replica ``r`` AND every
        replica upgraded earlier in this cycle re-cycle (drain →
        bring-up on the old weights), so the abort leaves the fleet
        fully serving ``old_version``, never a mixed-version state.
        Work reclaimed from a rolled-back replica was pinned to the NEW
        generation; once no replica of it remains, the router releases
        the pin (structured event) and the replay re-decodes on the old
        weights — zero requests lost either way."""
        self._event("serve_upgrade_abort", replica=r.index, to=version,
                    error=error)
        rollback = [x for x in self.replicas
                    if x.state != RETIRED and x.version == version]
        for x in rollback:
            with self._ctl_lock:
                self._fence_and_reclaim(x, self.clock(),
                                        reason="upgrade rollback")
                x.canary = False
                x.version = old_version
                x.params_override = None
                x.ckpt_override = None
                self._bring_up(x, self.clock())
            # bounded wait for the rollback engine; a replica that
            # cannot even serve the OLD weights re-enters the circuit
            # breaker, which is the failover path's problem, not the
            # upgrade's
            self._drive_until(lambda x=x: self._replica_serving(x),
                              timeout_s)
        # the aborted generation's canary references must not outlive
        # the abort: a RETRY of the same version name compares its
        # replica-0 canaries against a fresh reference, not the failed
        # attempt's tokens (which may have come from a bad checkpoint)
        for k in [k for k in self._canary_ref if k[0] == version]:
            del self._canary_ref[k]
        raise UpgradeAborted(S.structured_event(
            "serve_upgrade_aborted", replica=r.index, to=version,
            error=error, rolled_back=[x.index for x in rollback],
            fleet_version=old_version,
            # the set ring's tail: the drain/bring-up/canary events of
            # the failed cycle ride the abort record itself
            flight=self.flight.tail(64)))

    def rolling_upgrade(self, *, version: str, params=None,
                        ckpt: Optional[str] = None,
                        canary_codes=None, canaries: int = 2,
                        replica_timeout_s: float = 300.0) -> dict:
        """Hot-swap the fleet's weights replica-by-replica with ZERO
        dropped requests (docs/SERVING.md 'Elastic fleet'). Per
        replica, in index order:

          1. DRAIN — fence + reclaim: its in-flight work replays on
             survivors still serving the OLD weights (version-pinned
             routing guarantees the replay lands on the generation
             that started it, so the tokens stay byte-identical);
          2. RESTART on the new weights — ``params`` (a new pytree,
             thread/pipe shapes) or ``ckpt`` (a new ``--worker_ckpt``
             path for checkpoint-path attach: each worker loads +
             validates locally, weights never cross the wire);
          3. HEALTH-GATE — ``canaries`` requests decoded by the new
             engine ALONE, token-compared against the first upgraded
             replica's canary tokens (every replica of a generation
             must provably sample identical streams; replica 0 of the
             cycle sets the reference). A canary error, token
             divergence, bring-up failure/timeout, or the replica
             dying mid-canary ABORTS: the replica rolls back to the
             old weights and the typed ``UpgradeAborted`` reports the
             fleet whole on the old version;
          4. UNDRAIN — the gated replica rejoins routing; next slot.

        After the last replica, the set-level weights/version are
        promoted so future bring-ups, scale-outs, and /stats all speak
        the new generation. Returns the structured upgrade record."""
        import numpy as np

        from dalle_pytorch_tpu.resilience import faults

        with self._ctl_lock:
            self._reject_mid_upgrade("upgrade")
            if not version or version == self.weights_version:
                raise self._scale_error(
                    "upgrade", reason="version_unchanged",
                    weights_version=self.weights_version)
            if (params is None) == (ckpt is None):
                raise self._scale_error(
                    "upgrade",
                    reason="need_exactly_one_of_params_or_ckpt")
            if ckpt is not None and self.worker_ckpt is None:
                raise self._scale_error(
                    "upgrade",
                    reason="ckpt_upgrade_needs_worker_ckpt_set")
            if params is not None and self.worker_ckpt is not None:
                raise self._scale_error(
                    "upgrade",
                    reason="params_upgrade_on_worker_ckpt_set")
            self._upgrading = True
        # EVERYTHING past the flag runs under the finally that clears
        # it — an exception anywhere here (even a bad canaries value)
        # must never leave the fleet permanently rejecting reshapes
        try:
            old_version = self.weights_version
            if canary_codes is None:
                # smallest-bucket probe; any valid prompt does — the
                # gate compares determinism across replicas, not
                # quality
                canary_codes = [(1,) * min(2, self.cfg.text_seq_len)]
            record = {"from": old_version, "to": version,
                      "canaries": int(canaries), "replicas": []}
            self._event("serve_upgrade_begin", to=version,
                        from_version=old_version,
                        replicas=self.n_replicas)
            for r in list(self.replicas):
                if r.state == RETIRED:
                    continue
                if r.state == DRAINED:
                    # an operator-drained replica stays DOWN — the
                    # drain contract ('down until undrain_replica')
                    # outranks the rollout. Its version label moves
                    # with the fleet at promote time, so a later
                    # undrain brings it up on the promoted set-level
                    # weights, correctly stamped; the skip is an event
                    # an operator can see, not a silent hole.
                    self._event("serve_upgrade_skip_drained",
                                replica=r.index, to=version)
                    record["replicas"].append(
                        {"replica": r.index, "skipped": "drained"})
                    continue
                t0 = time.perf_counter()
                # the drain-race fault row: a real SIGKILL landing just
                # as the planned drain begins — reclaim-from-shadow
                # absorbs it identically (the fence kills a corpse)
                faults.on_upgrade_drain(
                    r.index,
                    getattr(r.engine, "pid", None)
                    if self.isolation == "process" else None)
                with self._ctl_lock:
                    # live-migrate first, version-pinned exactly like
                    # replay: only survivors still serving THIS
                    # replica's (old) generation may take its work
                    # mid-stream — same-seed tokens are byte-identical
                    # per weights_version, not across them
                    # racelint: disable=RL003 — deliberate: upgrade
                    # migration runs under _ctl_lock like every other
                    # reshape; see drain() for the full rationale
                    migrated = self._migrate_from(
                        r, self.clock(),
                        reason=f"rolling upgrade to {version}",
                        pin_version=r.version)
                    reclaimed = self._fence_and_reclaim(
                        r, self.clock(),
                        reason=f"rolling upgrade to {version}")
                    r.version = version
                    r.params_override = params
                    r.ckpt_override = ckpt
                    r.canary = True
                    self._bring_up(r, self.clock())
                if not self._drive_until(
                        lambda: self._replica_serving(r),
                        replica_timeout_s):
                    self._abort_upgrade(
                        r, version, old_version,
                        f"bring-up on new weights timed out "
                        f"(> {replica_timeout_s:g}s): {r.last_error}",
                        replica_timeout_s)
                # captured AFTER serving is confirmed: a circuit-breaker
                # retry DURING bring-up (flaky first spawn) is the
                # supervisor doing its job, not a death — only a
                # bring-up count moving while canaries are in flight
                # means the fresh engine died under the gate
                bringups0 = r.bringups
                handles = self._submit_canaries(r, version,
                                                canary_codes, canaries)
                self._drive_until(
                    lambda: all(h.done() for h in handles)
                    or r.bringups != bringups0
                    or not self._replica_serving(r),
                    replica_timeout_s)
                if r.bringups != bringups0 \
                        or not self._replica_serving(r):
                    self._abort_upgrade(
                        r, version, old_version,
                        f"replica died during canary: {r.last_error}",
                        replica_timeout_s)
                if not all(h.done() for h in handles):
                    self._abort_upgrade(
                        r, version, old_version,
                        f"canaries not answered within "
                        f"{replica_timeout_s:g}s", replica_timeout_s)
                try:
                    for k, h in enumerate(handles):
                        res = h.result(timeout=0)
                        if res.status != S.OK:
                            raise RuntimeError(
                                f"canary {k}: {res.status} "
                                f"({res.reason})")
                        if res.weights_version != version:
                            raise RuntimeError(
                                f"canary {k} stamped "
                                f"{res.weights_version!r}, expected "
                                f"{version!r}")
                        toks = np.asarray(res.tokens)
                        ref = self._canary_ref.setdefault(
                            (version, k), toks)
                        if not np.array_equal(toks, ref):
                            raise RuntimeError(
                                f"canary {k} tokens diverged from the "
                                f"generation reference — two replicas "
                                f"of {version!r} must sample "
                                f"byte-identical streams")
                    faults.on_canary_gate(r.index, version)
                except Exception as e:  # noqa: BLE001 — typed abort
                    self._abort_upgrade(r, version, old_version,
                                        f"canary gate failed: {e}",
                                        replica_timeout_s)
                r.canary = False
                self._event("serve_upgrade_replica", replica=r.index,
                            to=version, migrated=migrated,
                            reclaimed=reclaimed,
                            canaries=len(handles),
                            wall_s=round(time.perf_counter() - t0, 3))
                record["replicas"].append({
                    "replica": r.index, "migrated": migrated,
                    "reclaimed": reclaimed,
                    "wall_s": round(time.perf_counter() - t0, 3)})
            with self._ctl_lock:
                # promote: the new generation is now the set's truth —
                # future bring-ups, scale-outs, and stats all speak it
                self.weights_version = version
                if params is not None:
                    self.params = params
                    if self.isolation == "process" \
                            and self.worker_ckpt is None:
                        import jax
                        self._np_params = jax.tree.map(np.asarray,
                                                       params)
                if ckpt is not None:
                    self.worker_ckpt = ckpt
                for r in self.replicas:
                    r.params_override = None
                    r.ckpt_override = None
                    if r.state == DRAINED:
                        # skipped above; its next bring-up serves the
                        # promoted set-level weights, so the label
                        # must say so
                        r.version = version
                self.upgrades += 1
            self._event("serve_upgrade_done", to=version,
                        from_version=old_version,
                        replicas=len(record["replicas"]))
            return record
        finally:
            # the flag was SET under _ctl_lock; clearing it unguarded
            # would let a concurrent reshape read a half-written False
            # interleaved with its own admission check (every with-
            # block inside the try has unwound by here, so this cannot
            # self-deadlock)
            with self._ctl_lock:
                self._upgrading = False

    # -- supervision --------------------------------------------------------

    def _check_replicas(self, now: float) -> bool:
        """One supervision sweep: crashed loops and missed heartbeats
        are fenced + reclaimed; circuit-broken replicas past their
        backoff get a bring-up attempt. Hang detection applies only to
        replicas with a live loop THREAD — in single-threaded drive the
        driver itself is the loop, so a hang would block the driver,
        and crashes surface synchronously in ``step_once``."""
        did = False
        # a serve-side jax.profiler capture (POST /admin/profile) is
        # PROCESS-global: while one is RUNNING on any thread-mode
        # replica, every replica in this process runs slower (TraceMe
        # overhead, stop-time serialization, core contention) — exempt
        # them all from the hang deadline exactly like ``compiling``
        # (operator-initiated, bounded at K chunks, and fencing mid-
        # capture would both lose the replica and leak the global
        # trace open). Engine.capturing is a started trace only: an
        # armed-but-unconsumed request must NOT suppress fencing (a
        # wedged replica that never reaches its next dispatch would
        # otherwise evade the deadline forever)
        capturing = self.isolation != "process" and any(
            r.engine is not None
            and getattr(r.engine, "capturing", None) is not None
            and r.engine.capturing()
            for r in self.replicas if r.state == RUNNING)
        for r in self.replicas:
            if r.state == RUNNING and self.isolation == "process":
                did = self._check_child(r, now) or did
            elif r.state == RUNNING:
                if r.dead:
                    self._failover(r, now,
                                   reason=f"crash: {r.last_error}")
                    did = True
                elif r.thread is not None and not r.thread.is_alive():
                    self._failover(r, now, reason="loop thread died")
                    did = True
                elif r.thread is not None and r.engine is not None \
                        and not r.engine.compiling \
                        and not capturing \
                        and now - r.engine.last_heartbeat \
                        > self.heartbeat_s:
                    # ``compiling`` exempts a known first-call trace/
                    # compile (seconds on a cold cache) from the hang
                    # deadline — a healthy replica mid-compile must not
                    # be fenced for being slow to warm up
                    self._failover(
                        r, now,
                        reason=f"missed heartbeat "
                               f"(> {self.heartbeat_s:g}s: hang)")
                    did = True
            elif r.state == BROKEN and now >= r.next_bringup_t:
                did = self._bring_up(r, now) or did
        return did

    def _check_child(self, r: _Replica, now: float) -> bool:
        """One supervision check of a RUNNING process replica — the two
        liveness signals layered: PID liveness with exit decoding (a
        SIGKILL/SIGSEGV/OOM death answers at the OS level even though
        the child can say nothing), then the missed-heartbeat deadline
        over the frame stream (a process that is alive but silent is
        wedged — it gets hard-killed and fenced like a hang). A child
        that dies BEFORE its READY frame is a bring-up failure, not a
        failover: it never held work, so it re-enters the circuit-
        breaker backoff with nothing to reclaim."""
        c = r.engine
        if c is None:
            return False
        if not c.ready:
            if c.crashed or c.poisoned or not c.alive_proc():
                c.hard_kill()
                self._bringup_fail_async(
                    r, now, f"child died in bring-up: "
                            f"{c.last_error or c.exit_desc()}")
                return True
            if now - c.started_t > self.spawn_timeout_s \
                    and not c.awaiting_operator:
                # an operator-attached worker has no spawn to time out:
                # the slot waits (unroutable, harmless) until a worker
                # dials in, and the deadline starts at attach
                c.hard_kill()
                self._bringup_fail_async(
                    r, now, f"child bring-up exceeded "
                            f"{self.spawn_timeout_s:g}s")
                return True
            return False
        if c.crashed:
            r.last_error = f"crash: {c.last_error}"
            self._failover(r, now, reason=r.last_error)
        elif c.poisoned:
            r.last_error = c.last_error
            self._failover(r, now, reason=r.last_error)
        elif not c.alive_proc():
            r.last_error = f"child exited: {c.exit_desc()}"
            self._failover(r, now, reason=r.last_error)
        else:
            # compiling exempts a child from the tight deadline but not
            # forever: compile_grace_s caps how long "still compiling"
            # is believable without a single frame. The failover reason
            # names the deadline that actually expired.
            if c.compiling:
                deadline, which = (max(self.heartbeat_s,
                                       self.compile_grace_s),
                                   "compile grace")
            else:
                deadline, which = self.heartbeat_s, "heartbeat"
            if now - c.last_heartbeat <= deadline:
                return False
            self._failover(
                r, now,
                reason=f"missed {which} deadline (> {deadline:g}s: "
                       f"hang)")
        return True

    def _bringup_fail_async(self, r: _Replica, now: float,
                            msg: str) -> None:
        """A spawned child that died or stalled before READY: count it
        against the circuit breaker exactly like a synchronous
        constructor failure."""
        c = r.engine
        r.engine, r.queue = None, None
        r.await_ready = False
        if c is not None:
            r.last_exit = c.exit_desc()
            c.fence()               # releases the dead child's pipe
            # routing is gated on ready, so the shadow is normally
            # empty — but never drop a handle on principle
            for h in c.reclaim():
                self.queue.requeue(h)
        r.attempt += 1
        self.bringup_failures += 1
        delay = self.bringup_policy.backoff(min(r.attempt - 1, 20))
        r.next_bringup_t = now + delay
        r.last_error = msg
        r.state = BROKEN
        self._event("serve_replica_bringup_fail", replica=r.index,
                    attempt=r.bringups - 1, consecutive=r.attempt,
                    backoff_s=round(delay, 3), error=msg,
                    exit=r.last_exit)

    def _pump_children(self, now: float) -> bool:
        """Drain every live child's pipe: absorb heartbeats/snapshots,
        fulfil harvested results, notice READY transitions. The one
        place process-mode results enter the parent — called from the
        control loop (threaded) and ``step_once`` (sync drive)."""
        did = False
        for r in self.replicas:
            c = r.engine
            if r.state != RUNNING or c is None:
                continue
            did = c.pump() or did
            if r.await_ready and c.ready:
                announced = c.worker_weights_version
                if announced and announced != r.version:
                    # a worker serving the WRONG generation must never
                    # join routing: during a rolling upgrade a stale
                    # dialer (or an operator pointing an old worker at
                    # a reshaped fleet) would silently decode on old
                    # weights — fence it as a bring-up failure instead
                    self._bringup_fail_async(
                        r, now,
                        f"worker announced weights {announced!r}, "
                        f"replica expects {r.version!r}")
                    did = True
                    continue
                r.await_ready = False
                r.attempt = 0
                r.last_error = ""
                r.conns += 1
                self._event("serve_replica_up", replica=r.index,
                            bringups=r.bringups, pid=c.pid,
                            transport=c.transport_kind, peer=c.peer,
                            weights_version=r.version)
                did = True
        return did

    # -- routing ------------------------------------------------------------

    def _expire(self, h: S.RequestHandle, now: float) -> None:
        req = h.request
        self.expired += 1
        self._hol_handoff.pop(req.request_id, None)
        self._version_holds.discard(req.request_id)
        self._event("serve_deadline", request_id=req.request_id,
                    where="queued", deadline_s=req.deadline_s,
                    waited_s=round(now - req.submit_t, 4))
        h.fulfill(S.Result(
            status=S.DEADLINE_EXCEEDED, request_id=req.request_id,
            reason=f"deadline_s={req.deadline_s:g} exceeded (queued)",
            weights_version=self.weights_version,
            queued_s=round(now - req.submit_t, 6),
            total_s=round(now - req.submit_t, 6)))

    def _capacity(self, r: _Replica) -> int:
        if self.isolation == "process":
            # parent-authoritative: the shadow (routed, unresolved) is
            # the truth; the child's own reports lag a frame. Allow one
            # queued wave beyond the slot pool so the child can prefill
            # its next group while decoding the current one.
            return max(0, 2 * r.engine.num_slots - len(r.engine.shadow))
        return max(0, r.engine.num_slots - r.engine.active_slots()
                   - r.queue.depth())

    def _pick(self, cands: List[_Replica], caps: dict,
              h: S.RequestHandle) -> _Replica:
        """Least-loaded with page-awareness: most free slot capacity
        first; among paged replicas, one whose pool can map the
        request's prompt span NOW beats one that would defer it, and
        free pages break remaining ties."""
        from dalle_pytorch_tpu.serve import kv_pool as KV

        pin = h.replay_version
        # a fenced/drained replica's HOL reservation, handed back at
        # reclaim: the EXACT (prefix-aware) page need, which beats the
        # blind full-span guess below — the retiring replica's claim
        # follows the request instead of dying with the engine
        handoff = self._hol_handoff.get(h.request.request_id)

        def score(r: _Replica):
            if pin is not None and r.version != pin:
                # the route-level candidate filter makes this
                # unreachable; decoding a pinned replay on another
                # generation's weights must be impossible, not unlikely
                raise ReplayVersionMismatch(S.structured_event(
                    "serve_replay_version_mismatch",
                    request_id=h.request.request_id, pinned=pin,
                    replica=r.index, version=r.version))
            eng = r.engine
            fits, free_pages = True, 0
            if eng.kv == "paged":
                if self.isolation == "process":
                    # last-frame view: pages_free lags one heartbeat
                    # (-1 = no frame yet -> stay optimistic); the
                    # child's own admission gate is the authority
                    free_pages = eng.pages_free
                    buckets, page_size = self._buckets, self._page_size
                    if free_pages < 0:
                        return (True, caps[r.index], 0, -r.index)
                else:
                    free_pages = eng.alloc.free
                    buckets, page_size = eng.buckets, eng.page_size
                try:
                    need = handoff if handoff is not None \
                        else KV.pages_for(
                            S.bucket_for(len(h.request.codes), buckets),
                            page_size)
                    fits = free_pages >= need
                except ValueError:
                    # an over-long prompt buckets nowhere; the engine's
                    # admission turns it into a typed error result
                    fits = True
            return (fits, caps[r.index], free_pages, -r.index)

        return max(cands, key=score)

    def _route(self, now: float) -> bool:
        """Move ready requests from the shared queue into per-replica
        private queues (a hand-off: ``requeue(count=False)`` keeps the
        handle's shared-queue identity and arrival position). Queued
        deadline expiries are reaped here on EVERY sweep — even with
        zero live replicas, a dead entry must get its typed result."""
        live = [r for r in self.replicas
                if r.state == RUNNING and r.engine is not None
                and not r.canary]
        # (canary replicas are serving, but only the upgrade's health
        # gate may talk to them — routing rejoins at gate pass)
        if self.isolation == "process":
            # routable = READY and believable: not poisoned/crashed and
            # the PID is live RIGHT NOW — never route into a corpse in
            # the window before the next supervision sweep fences it
            live = [r for r in live
                    if r.engine.ready and not r.engine.poisoned
                    and not r.engine.crashed and not r.engine.fenced
                    and r.engine.alive_proc()]
        caps = {r.index: self._capacity(r) for r in live}
        total = sum(caps.values())
        ready, expired = self.queue.pop_ready(total, now)
        for h in expired:
            self._expire(h, now)
        assigned: dict = {}
        for h in ready:
            pin = h.replay_version
            cands = [r for r in live if caps[r.index] > 0
                     and (pin is None or r.version == pin)]
            # role preference: every admission (fresh or replay) needs
            # a prefill, so decode-specialized replicas are offered
            # work only when no prefill-capable candidate has capacity
            # — a PREFERENCE: zero-loss progress outranks the role
            # split, so the fallback to any candidate is automatic
            preferred = [r for r in cands if r.role != "decode"]
            cands = preferred or cands
            if not cands:
                # version-pinned replay with no same-generation
                # capacity right now: hold or release, never mis-route
                self._route_hold(h, pin)
                continue
            r = self._pick(cands, caps, h)
            if pin is None:
                # pin at first routing: from here on, failover replay
                # of this request goes only to this weights generation
                h.replay_version = r.version
            if h.trace is not None:
                # the shared-queue wait closes here; the zero-duration
                # route marker carries WHERE the request went (the
                # engine-side spans then tile from this instant)
                if not h.trace.has_in_attempt("queue_wait"):
                    self.flight.record(h.trace.span("queue_wait", now))
                self.flight.record(h.trace.span(
                    "route", now, replica=r.index,
                    weights_version=r.version))
            self._hol_handoff.pop(h.request.request_id, None)
            self._version_holds.discard(h.request.request_id)
            caps[r.index] -= 1
            if self.isolation == "process":
                assigned.setdefault(r.index, (r, []))[1].append(h)
            else:
                r.queue.requeue(h, count=False)
        for r, batch in assigned.values():
            r.engine.route(batch)       # one admit frame per replica
        return bool(ready or expired)

    def _route_hold(self, h: S.RequestHandle,
                    pin: Optional[str]) -> None:
        """A popped request the router cannot place THIS sweep. A
        version-pinned replay whose generation still exists somewhere
        in the fleet (busy, circuit-broken, draining — it may come
        back) is HELD at its original arrival position; one whose
        generation has left the fleet entirely (the upgrade completed
        under it) has its pin RELEASED — zero-loss outranks a stale
        pin, the request re-decodes from scratch on the current
        weights, and its Result is stamped with the version that
        actually produced the tokens. Both paths are structured
        events, fired once per request."""
        rid = h.request.request_id
        if pin is not None and not any(
                rr.version == pin and rr.state != RETIRED
                for rr in self.replicas):
            h.replay_version = None
            self._version_holds.discard(rid)
            self._event("serve_replay_version_released",
                        request_id=rid, pinned=pin,
                        fleet_version=self.weights_version)
        elif rid not in self._version_holds:
            self._version_holds.add(rid)
            self._event("serve_replay_version_hold", request_id=rid,
                        pinned=pin)
        self.queue.requeue(h, count=False)

    # -- the replica loop (threaded mode) -----------------------------------

    def _spawn(self, r: _Replica) -> None:
        r.thread = threading.Thread(
            target=self._run_replica, args=(r, r.engine, r.stop),
            daemon=True, name=f"serve-replica-{r.index}")
        r.thread.start()

    def _run_replica(self, r: _Replica, engine, stop) -> None:
        """One replica's serving loop. A step exception is a CRASH —
        recorded for the supervisor, loop exits (contrast the single-
        engine ``Engine.run``, which fails the in-slot requests in
        place: here the supervisor replays them instead, so the callers
        get their exact tokens, not typed errors). A fence (failover
        decided while this thread was wedged) ends the loop on the next
        iteration."""
        from dalle_pytorch_tpu.resilience import faults
        while not stop.is_set() and not engine.fenced:
            try:
                faults.on_replica_chunk(
                    r.index, engine.decode_steps // engine.chunk_steps)
                busy = engine.step_once()
            except Exception as e:  # noqa: BLE001 — supervised crash
                if engine.fenced or r.engine is not engine:
                    # a ZOMBIE crashing: this engine was already fenced
                    # and replaced (e.g. a wedge that finally errored
                    # out) — its requests were reclaimed long ago, and
                    # flagging r.dead now would fail over the healthy
                    # replacement that owns r
                    return
                r.last_error = repr(e)
                r.dead = True
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                return
            if not busy and engine.idle():
                stop.wait(self._idle_sleep_s)

    def _run_control(self, stop: threading.Event) -> None:
        """Routing + supervision loop (threaded mode). In process mode
        this is the ONLY parent-side loop: the children drive their own
        engines, and this thread pumps their pipes, routes, and
        supervises."""
        while not stop.is_set():
            now = self.clock()
            with self._ctl_lock:
                busy = False
                if self.isolation == "process":
                    busy = self._pump_children(now)
                busy = self._check_replicas(now) or busy
                busy = self._route(now) or busy
                # racelint: disable=RL003 — deliberate: role handoff is
                # a reshape (warm prefill→decode migration) and runs
                # under _ctl_lock like drain/scale-in/upgrade
                busy = self._role_handoff(now) or busy
            stop.wait(0.0005 if busy else self._idle_sleep_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaSet":
        """Threaded mode: one loop thread per live replica plus the
        control thread (routing + supervision)."""
        self._started = True
        if self._t_start is None:       # threaded mode never steps
            self._t_start = self.clock()  # sync, so stamp elapsed here
        if self.isolation != "process":  # children ARE the loops
            for r in self.replicas:
                if r.state == RUNNING and r.thread is None:
                    self._spawn(r)
        self._ctl_stop = threading.Event()
        self._ctl_thread = threading.Thread(
            target=self._run_control, args=(self._ctl_stop,),
            daemon=True, name="serve-replica-control")
        self._ctl_thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Stop supervision, then every replica loop, joining each with
        its share of the deadline. A replica that OUTLIVES its join
        (wedged in a step) is fenced so it can never fulfil or requeue
        later; either way its private queue is drained and every
        still-open handle — queued or in-slot — is fulfilled
        ``cancelled`` lock-free (first-write-wins makes the late-waker
        race harmless). Callers are never stranded."""
        t0 = time.perf_counter()
        self._ctl_stop.set()
        if self._ctl_thread is not None:
            self._ctl_thread.join(timeout)
        if self.isolation == "process":
            with self._ctl_lock:
                for r in self.replicas:
                    c = r.engine
                    if c is None:
                        continue
                    left = max(0.5, timeout - (time.perf_counter() - t0))
                    # graceful SHUTDOWN -> join -> SIGKILL straggler;
                    # close() salvages the pipe and fences, so a child
                    # outliving its join can never fulfil anything late
                    c.close(left / max(self.n_replicas, 1))
                    for h in c.reclaim():
                        h.fulfill(S.Result(
                            status=S.CANCELLED,
                            request_id=h.request.request_id,
                            reason="server shutdown"))
                if self.listener is not None:
                    self.listener.close()
            return
        with self._ctl_lock:
            for r in self.replicas:
                if r.stop is not None:
                    r.stop.set()
            for r in self.replicas:
                if r.thread is not None:
                    left = max(0.1, timeout - (time.perf_counter() - t0))
                    r.thread.join(left / max(len(self.replicas), 1))
            for r in self.replicas:
                eng, q = r.engine, r.queue
                if r.thread is not None and r.thread.is_alive() \
                        and eng is not None:
                    eng.fence()
                handles = []
                if q is not None:
                    handles.extend(q.drain())
                if eng is not None:
                    handles.extend(eng.inflight_handles())
                for h in handles:
                    if not h.done():
                        h.fulfill(S.Result(
                            status=S.CANCELLED,
                            request_id=h.request.request_id,
                            reason="server shutdown"))

    # -- single-threaded drive (tests, bench) -------------------------------

    def step_once(self) -> bool:
        """One set iteration: supervise (bring-ups, crash cleanup),
        route, then step every live replica once. Crashes fail over
        INLINE — the same fence/reclaim/replay path the threaded
        supervisor takes, just synchronously."""
        from dalle_pytorch_tpu.resilience import faults
        now = self.clock()
        if self._t_start is None:
            self._t_start = now
        with self._ctl_lock:
            did = False
            if self.isolation == "process":
                did = self._pump_children(now)
            did = self._check_replicas(now) or did
            did = self._route(now) or did
            # racelint: disable=RL003 — deliberate: same reshape-under-
            # _ctl_lock discipline as the driver loop above
            did = self._role_handoff(now) or did
        if self.isolation == "process":
            # the children step themselves; the parent's "step" is the
            # pump/supervise/route above. Nap briefly when nothing
            # moved so run_until_idle doesn't hot-spin while children
            # decode at their own pace.
            if not did:
                time.sleep(0.001)
            return did
        for r in list(self.replicas):
            if r.state != RUNNING or r.engine is None:
                continue
            eng = r.engine
            try:
                faults.on_replica_chunk(
                    r.index, eng.decode_steps // eng.chunk_steps)
                did = eng.step_once() or did
            except Exception as e:  # noqa: BLE001 — supervised crash
                r.last_error = repr(e)
                self._event("serve_replica_crash", replica=r.index,
                            error=repr(e))
                with self._ctl_lock:
                    self._failover(r, self.clock(),
                                   reason=f"crash: {e!r}")
                did = True
        return did

    def idle(self) -> bool:
        if self.queue.depth() > 0:
            return False
        if self.isolation == "process":
            # the shadow is the parent-side truth: anything routed and
            # unresolved is still in flight somewhere
            return all(not r.engine.shadow for r in self.replicas
                       if r.engine is not None)
        for r in self.replicas:
            if r.queue is not None and r.queue.depth() > 0:
                return False
            if r.engine is not None and (r.engine.active_slots() > 0
                                         or r.engine._pending):
                return False
        return True

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            busy = self.step_once()
            if not busy and self.idle():
                return
        raise RuntimeError(
            f"replica set did not go idle in {max_steps} steps")

    # -- aggregate counters (bench._serve_load_point's surface) -------------

    def _agg(self, name: str) -> int:
        return self._retired[name] + sum(
            getattr(r.engine, name, 0) for r in self.replicas
            if r.engine is not None)

    @property
    def tokens_decoded(self) -> int:
        return self._agg("tokens_decoded")

    @property
    def decode_steps(self) -> int:
        return self._agg("decode_steps")

    @property
    def harvests(self) -> int:
        return self._agg("harvests")

    @property
    def occupancy_sum(self) -> int:
        return self._agg("occupancy_sum")

    @property
    def completed(self) -> int:
        return self._agg("completed")

    # -- observability ------------------------------------------------------

    def alive(self) -> bool:
        """True while at least one replica serves (healthz contract:
        503 only when ALL are dead)."""
        for r in self.replicas:
            if r.state != RUNNING or r.engine is None:
                continue
            if self.isolation == "process":
                if r.engine.alive_proc():
                    return True
            elif r.thread is None or r.thread.is_alive():
                return True
        return False

    def replica_states(self) -> List[dict]:
        """Per-replica /healthz body. Process mode adds the supervised-
        child facts an operator triages with: the child PID, its
        restart count, the decoded last exit (signal name / OOM exit
        137 / plain code), and the child's reported RSS."""
        now = self.clock()
        out = []
        for r in self.replicas:
            if self.isolation == "process":
                alive = r.state == RUNNING and r.engine is not None \
                    and r.engine.alive_proc()
            else:
                alive = r.state == RUNNING and r.engine is not None and \
                    (r.thread is None or r.thread.is_alive())
            rec = {"replica": r.index, "state": r.state, "alive": alive,
                   "bringups": r.bringups,
                   "weights_version": r.version, "role": r.role}
            if r.canary:
                rec["canary"] = True    # upgrading: gate-only, unrouted
            if r.engine is not None:
                rec["heartbeat_age_s"] = round(
                    max(now - r.engine.last_heartbeat, 0.0), 4)
            if self.isolation == "process":
                rec["restarts"] = max(r.bringups - 1, 0)
                rec["reconnects"] = max(r.conns - 1, 0)
                if r.engine is not None:
                    rec["pid"] = r.engine.pid
                    rec["rss_mb"] = r.engine.rss_mb
                    rec["ready"] = r.engine.ready
                    rec.update(r.engine.transport_info(now))
                if r.last_exit:
                    rec["last_exit"] = r.last_exit
            if r.last_error:
                rec["last_error"] = r.last_error
            out.append(rec)
        return out

    def decode_compiles_per_replica(self) -> List[int]:
        """Each LIVE replica's decode-program trace count — the
        one-compile-per-replica contract bench_serve asserts (a
        replaced engine is a fresh program, counted on its own)."""
        return [r.engine.decode_traces for r in self.replicas
                if r.engine is not None]

    def _kv_bytes_per_shard(self) -> int:
        """Per-shard KV residency — where one device of a replica's
        slice actually holds the pool (/stats mesh satellite). Read off
        a live thread-mode engine; MODELED from config for child-process
        engines, whose pools live in other interpreters."""
        if self.isolation != "process":
            for r in self.replicas:
                if r.engine is not None:
                    return r.engine._mesh_stats()[
                        "kv_hbm_bytes_per_shard"]
        from dalle_pytorch_tpu.serve import kv_pool as KV
        kw = self._engine_kwargs
        try:
            dtype_bytes = self.params["text_emb"]["w"].dtype.itemsize
        except (TypeError, KeyError, AttributeError):
            dtype_bytes = 4     # worker_ckpt mode may carry no params
        total = KV.modeled_kv_bytes(
            self.cfg.transformer, kv=self.kv,
            num_slots=kw["num_slots"], total_len=self.cfg.seq_len,
            page_size=kw["page_size"], num_pages=kw["num_pages"],
            quantized=kw["quantize_cache"], dtype_bytes=dtype_bytes)
        from dalle_pytorch_tpu.parallel.serve_specs import kv_heads_shard
        m = self.devices_per_replica
        if m > 1 and kv_heads_shard(self.cfg.transformer.heads, m):
            return total // m   # heads-sharded pool divides exactly
        return total

    def stats(self) -> dict:
        # lazy (the serve package's jax-free-import discipline):
        # serve_specs pulls jax, and by stats() time a backend exists
        from dalle_pytorch_tpu.parallel.serve_specs import \
            SERVE_AXIS as _SERVE_AXIS
        elapsed = None if self._t_start is None \
            else max(self.clock() - self._t_start, 1e-9)
        live = [r for r in self.replicas if r.engine is not None]
        proc = self.isolation == "process"
        per = []
        for r in self.replicas:
            rec = {"replica": r.index, "state": r.state,
                   "weights_version": r.version, "role": r.role}
            if r.engine is not None:
                e = r.engine
                rec.update({
                    "active_slots": e.active_slots(),
                    # routed-but-not-decoding: the shadow holds EVERY
                    # outstanding request (in-slot ones included), so
                    # subtract the active count rather than adding the
                    # child's own queue depth on top — same meaning as
                    # thread mode's private-queue depth
                    "queued": (max(len(e.shadow) - e.active_slots(), 0)
                               if proc
                               else (r.queue.depth() if r.queue else 0)),
                    "decode_compiles": e.decode_traces,
                    "prefill_compiles": e.prefill_traces,
                    "completed": e.completed,
                    "tokens_decoded": e.tokens_decoded,
                })
                if proc:
                    rec.update({"pid": e.pid, "rss_mb": e.rss_mb,
                                "restarts": max(r.bringups - 1, 0),
                                "reconnects": max(r.conns - 1, 0)})
                    rec.update(e.transport_info())
                    if r.last_exit:
                        rec["last_exit"] = r.last_exit
                    if e.kv == "paged" and e.pages_free >= 0:
                        rec["pages_free"] = e.pages_free
                elif e.kv == "paged":
                    rec["pages_free"] = e.alloc.free
            per.append(rec)
        tokens = self.tokens_decoded
        steps = self.decode_steps
        out = {
            "replicas": self.n_replicas,
            "isolation": self.isolation,
            # mesh observability (/stats satellite): how many devices
            # each replica's engine spans, and the mesh shape when > 1
            "devices_per_replica": self.devices_per_replica,
            "mesh_shape": (
                {_SERVE_AXIS: self.devices_per_replica}
                if self.devices_per_replica > 1 else None),
            "kv_hbm_bytes_per_shard": self._kv_bytes_per_shard(),
            "alive_replicas": sum(
                1 for r in self.replicas
                if r.state == RUNNING and r.engine is not None),
            "kv": self.kv,
            "queue_depth": self.queue.depth() + sum(
                r.queue.depth() for r in live if r.queue is not None),
            "num_slots": sum(r.engine.num_slots for r in live),
            "active_slots": sum(r.engine.active_slots() for r in live),
            "chunk_steps": self._engine_kwargs["chunk_steps"],
            "decode_steps": steps,
            "tokens_decoded": tokens,
            "tokens_per_s": (round(tokens / elapsed, 2)
                             if elapsed else 0.0),
            "mean_occupancy": round(self.occupancy_sum / max(steps, 1),
                                    3),
            "completed": self.completed,
            "expired": self._agg("expired") + self.expired,
            "rejected": self.queue.rejected,
            "requeued": self.queue.requeued,
            "decode_compiles": self._agg("decode_traces"),
            "prefill_compiles": self._agg("prefill_traces"),
            "harvests": self.harvests,
            "host_round_trips_per_token": round(
                self.harvests / max(tokens, 1), 6),
            "failovers": self.failovers,
            "reclaimed": self.reclaimed,
            "bringup_failures": self.bringup_failures,
            "evicted": self._agg("evicted"),
            # the cell-stats surface: fleet-wide prefix reuse for this
            # set, aggregated across replicas (retired ones included) —
            # what the gateway's affinity bench reads per CELL
            "prefix_hits": self._agg("prefix_hits"),
            "prefix_entries": sum(
                len(r.engine.prefix) for r in live
                if getattr(r.engine, "prefix", None) is not None),
            # the elastic surface: current generation, reshape
            # counters, and whether a rolling upgrade owns the fleet
            "weights_version": self.weights_version,
            "max_replicas": self.max_replicas,
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "upgrades": self.upgrades,
            "upgrading": self._upgrading,
            # live KV migration (drain/scale-in/upgrade/role handoff)
            "migrations": self.migrations,
            "migrate_fallbacks": self.migrate_fallbacks,
            "migrated_tokens_saved": self.migrated_tokens_saved,
            "hol_handoffs": self.hol_handoffs,
            "flight_events": len(self.flight),
            "per_replica": per,
        }
        if proc:
            out["transport"] = self.transport
            if self.listener is not None:
                # where a remote worker dials in, how many dialers the
                # HELLO gate turned away, and which replica indices are
                # currently open for attach (runtime-born slots
                # included — the registry is never startup-static)
                out["worker_endpoint"] = self.listener.endpoint
                out["attach_rejected"] = self.listener.rejected
                out["attach_expected"] = self.listener.expected_indices()
        return out
