"""Model layer (L4): DiscreteVAE, DALLE, CLIP — init/apply pairs + wrappers.

Mirrors the reference's three-model public surface
(reference dalle_pytorch/__init__.py:1) on the functional ops layer.
"""

from dalle_pytorch_tpu.models.vae import DiscreteVAE, VAEConfig  # noqa: F401
from dalle_pytorch_tpu.models.dalle import DALLE, DALLEConfig  # noqa: F401
from dalle_pytorch_tpu.models.clip import CLIP, CLIPConfig  # noqa: F401
