"""DiscreteVAE — conv encoder/decoder with a Gumbel-softmax discrete codebook.

Capability parity with the reference DiscreteVAE (reference
dalle_pytorch/dalle_pytorch.py:65-157): images -> per-position token logits ->
Gumbel-softmax relaxed one-hot -> codebook mix -> conv decoder, plus the two
token-space entry points DALLE depends on, ``get_codebook_indices`` (argmax
tokens, reference :120-124) and ``decode`` (tokens -> image, reference
:126-136).

TPU-first design choices:
  * NHWC activations and HWIO kernels end-to-end — the layout XLA:TPU tiles
    onto the MXU without transposes (the reference is NCHW, torch's layout);
  * the codebook mix is one ``(b*h*w, num_tokens) @ (num_tokens, dim)``
    matmul — MXU-shaped — instead of a per-pixel einsum;
  * Gumbel noise comes from an explicit PRNG key (stateless, shardable);
  * ``apply`` is pure and jit/pjit-compatible; the training CLI shards it
    over the batch axis of a device mesh.

Architecture contract (matching reference __init__, :76-117): ``num_layers``
stride-2 4x4 conv+ReLU downsampling stages (so token grid = image_size /
2**num_layers), optional ResNet blocks at the encoder tail / decoder head,
a 1x1 conv to ``num_tokens`` logits, and a mirrored ConvTranspose decoder.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.ops import core

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    image_size: int = 256
    num_tokens: int = 512
    codebook_dim: int = 512
    num_layers: int = 3
    num_resnet_blocks: int = 0
    hidden_dim: int = 64
    channels: int = 3
    temperature: float = 0.9
    # Reference F.gumbel_softmax default is hard=False (soft relaxation,
    # reference dalle_pytorch.py:149); True gives straight-through.
    straight_through: bool = False

    def __post_init__(self):
        if not math.log2(self.image_size).is_integer():
            raise ValueError("image size must be a power of 2")
        if self.num_layers < 1:
            raise ValueError("number of layers must be >= 1")

    @property
    def grid_size(self) -> int:
        return self.image_size // (2 ** self.num_layers)

    @property
    def image_seq_len(self) -> int:
        return self.grid_size ** 2


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def _resblock_init(key: Array, chan: int, dtype) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "c1": core.conv2d_init(k1, chan, chan, 3, dtype=dtype),
        "c2": core.conv2d_init(k2, chan, chan, 3, dtype=dtype),
        "c3": core.conv2d_init(k3, chan, chan, 1, dtype=dtype),
    }


def vae_init(key: Array, cfg: VAEConfig, dtype=jnp.float32) -> dict:
    """Build the parameter pytree. Channel plan mirrors the reference
    (dalle_pytorch.py:88-117): encoder channels [C, h, h, ...], decoder is
    the reverse, decoder input = codebook_dim (or a 1x1 stem when resblocks
    are present)."""
    n = cfg.num_layers
    keys = iter(jax.random.split(key, 4 * n + 2 * cfg.num_resnet_blocks + 8))

    params: dict = {
        "codebook": core.embedding_init(next(keys), cfg.num_tokens,
                                        cfg.codebook_dim, dtype),
    }

    enc_chans = [cfg.channels] + [cfg.hidden_dim] * n
    params["enc_convs"] = [
        core.conv2d_init(next(keys), cin, cout, 4, dtype=dtype)
        for cin, cout in zip(enc_chans[:-1], enc_chans[1:])
    ]
    params["enc_res"] = [
        _resblock_init(next(keys), enc_chans[-1], dtype)
        for _ in range(cfg.num_resnet_blocks)
    ]
    params["enc_out"] = core.conv2d_init(next(keys), enc_chans[-1],
                                         cfg.num_tokens, 1, dtype=dtype)

    has_res = cfg.num_resnet_blocks > 0
    dec_chans = [cfg.hidden_dim] * n
    dec_in = dec_chans[0] if has_res else cfg.codebook_dim
    if has_res:
        params["dec_stem"] = core.conv2d_init(next(keys), cfg.codebook_dim,
                                              dec_chans[0], 1, dtype=dtype)
    params["dec_res"] = [
        _resblock_init(next(keys), dec_chans[0], dtype)
        for _ in range(cfg.num_resnet_blocks)
    ]
    dec_io = list(zip([dec_in] + dec_chans[:-1], dec_chans))
    params["dec_convs"] = [
        core.conv2d_init(next(keys), cin, cout, 4, dtype=dtype)
        for cin, cout in dec_io
    ]
    params["dec_out"] = core.conv2d_init(next(keys), dec_chans[-1],
                                         cfg.channels, 1, dtype=dtype)
    return params


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------

def _resblock(p: dict, x: Array) -> Array:
    h = jax.nn.relu(core.conv2d(p["c1"], x, padding=1))
    h = jax.nn.relu(core.conv2d(p["c2"], h, padding=1))
    return core.conv2d(p["c3"], h) + x


def encode_logits(params: dict, images: Array) -> Array:
    """images (b, H, W, C) in [-1, 1] -> logits (b, h, w, num_tokens)."""
    x = images
    for p in params["enc_convs"]:
        x = jax.nn.relu(core.conv2d(p, x, stride=2, padding=1))
    for p in params["enc_res"]:
        x = _resblock(p, x)
    return core.conv2d(params["enc_out"], x)


def decode_embeds(params: dict, embeds: Array) -> Array:
    """embeds (b, h, w, codebook_dim) -> images (b, H, W, C)."""
    x = embeds
    if "dec_stem" in params:
        x = core.conv2d(params["dec_stem"], x)
    for p in params["dec_res"]:
        x = _resblock(p, x)
    for p in params["dec_convs"]:
        x = jax.nn.relu(core.conv2d_transpose(p, x, stride=2, padding=1))
    return core.conv2d(params["dec_out"], x)


def gumbel_softmax(key: Array, logits: Array, tau: float,
                   straight_through: bool = False) -> Array:
    """Relaxed one-hot over the last axis (token dim). Soft by default, like
    the reference's F.gumbel_softmax(hard=False) (dalle_pytorch.py:149)."""
    g = jax.random.gumbel(key, logits.shape, logits.dtype)
    soft = jax.nn.softmax((logits + g) / tau, axis=-1)
    if straight_through:
        hard = jax.nn.one_hot(jnp.argmax(soft, axis=-1), logits.shape[-1],
                              dtype=soft.dtype)
        soft = soft + jax.lax.stop_gradient(hard - soft)
    return soft


def vae_apply(params: dict, images: Array, *, cfg: VAEConfig,
              rng: Optional[Array] = None,
              temperature: Optional[float] = None,
              return_logits: bool = False,
              return_recon_loss: bool = False):
    """Forward pass (reference DiscreteVAE.forward, dalle_pytorch.py:138-157).

    ``temperature`` overrides cfg.temperature so the training CLI's per-epoch
    schedule (reference trainVAE.py:78,104-105) stays a traced scalar, not a
    recompile.
    """
    logits = encode_logits(params, images)
    if return_logits:
        return logits

    if rng is None:
        raise ValueError("vae_apply needs an explicit PRNG key for the "
                         "Gumbel noise (stateless JAX RNG)")
    tau = cfg.temperature if temperature is None else temperature
    soft = gumbel_softmax(rng, logits, tau, cfg.straight_through)

    # (b, h, w, T) @ (T, d) — one big MXU matmul.
    embeds = jnp.einsum("bhwt,td->bhwd", soft,
                        params["codebook"]["w"].astype(soft.dtype))
    recon = decode_embeds(params, embeds)

    if not return_recon_loss:
        return recon
    return jnp.mean(jnp.square(images - recon))


def get_codebook_indices(params: dict, images: Array) -> Array:
    """(b, H, W, C) -> (b, image_seq_len) int32, argmax over the token dim,
    flattened row-major over the (h, w) grid (reference
    dalle_pytorch.py:120-124). No gradient flows (argmax)."""
    logits = encode_logits(params, images)
    b, h, w, t = logits.shape
    return jnp.argmax(logits, axis=-1).reshape(b, h * w).astype(jnp.int32)


def decode(params: dict, img_seq: Array,
           codebook: Optional[Array] = None) -> Array:
    """Token ids (b, n) -> images (b, H, W, C), assuming a square grid
    (reference dalle_pytorch.py:126-136).

    ``codebook`` optionally overrides the VAE's own table — DALLE training
    updates the tied codebook (reference dalle_pytorch.py:283), so decoding
    after DALLE training must use DALLE's copy.
    """
    table = params["codebook"]["w"] if codebook is None else codebook
    embeds = jnp.take(table, img_seq, axis=0)
    b, n, d = embeds.shape
    g = int(math.isqrt(n))
    assert g * g == n, "image token sequence must form a square grid"
    return decode_embeds(params, embeds.reshape(b, g, g, d))


# ---------------------------------------------------------------------------
# OO wrapper for reference-API parity
# ---------------------------------------------------------------------------

class DiscreteVAE:
    """Thin stateful wrapper over the functional core, mirroring the
    reference class surface (reference dalle_pytorch/dalle_pytorch.py:65-157)
    for users arriving from DALLE-pytorch. All compute delegates to the pure
    functions above; ``self.params`` is the single source of truth and can be
    swapped wholesale (checkpoint restore, optimizer updates)."""

    def __init__(self, key: Optional[Array] = None, *, params: dict = None,
                 dtype=jnp.float32, **cfg_kwargs):
        self.config = VAEConfig(**cfg_kwargs)
        if params is None:
            if key is None:
                key = jax.random.PRNGKey(0)
            params = vae_init(key, self.config, dtype)
        self.params = params

    # reference-parity properties
    @property
    def image_size(self) -> int:
        return self.config.image_size

    @property
    def num_tokens(self) -> int:
        return self.config.num_tokens

    @property
    def num_layers(self) -> int:
        return self.config.num_layers

    @property
    def temperature(self) -> float:
        return self.config.temperature

    def __call__(self, images: Array, rng: Optional[Array] = None, **kw):
        return vae_apply(self.params, images, cfg=self.config, rng=rng, **kw)

    forward = __call__

    def get_codebook_indices(self, images: Array) -> Array:
        return get_codebook_indices(self.params, images)

    def decode(self, img_seq: Array, codebook: Optional[Array] = None):
        return decode(self.params, img_seq, codebook)
