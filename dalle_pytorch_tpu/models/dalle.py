"""DALLE — joint text+image autoregressive transformer, TPU-native.

Capability parity with the reference DALLE (reference
dalle_pytorch/dalle_pytorch.py:241-407):

  * vocab layout ``[0, num_text_tokens) text | [.., +num_image_tokens) image
    | last = EOS`` (reference :277,303-315,403);
  * per-position logits mask: positions < text_seq_len-1 predict text ids
    only, positions >= text_seq_len-1 predict image ids only, EOS only at
    the final position (reference :303-315) — mask row i governs the token
    PREDICTED there, i.e. token i+1;
  * the image embedding is TIED to the VAE codebook (reference :283).  In
    this functional design DALLE *owns* the table: ``dalle_init`` seeds
    ``params['image_emb']`` from the VAE codebook, DALLE training updates it,
    and ``generate_images`` decodes through the VAE convs with DALLE's copy
    (``models.vae.decode(codebook=...)``) — same semantics as the reference's
    shared module, explicit instead of aliased;
  * axial image position embedding.  Default factorizes over the real token
    grid; ``axial_compat='full_image'`` reproduces the reference quirk of a
    (image_size × image_size) table of which only the first image_seq_len
    rows are used (reference :268, SURVEY.md §5 "axial pos-emb quirk");
  * training loss: one CE over all positions, labels = [text, image+offset]
    shifted left with EOS appended (reference :398-406);
  * ``generate_images``: top-k (keep (1-thres)·vocab) then temperature
    categorical (reference :41-47,339-341) — but as ONE jit-compiled
    ``lax.scan`` with an on-device KV cache (ops.decode) instead of a python
    loop of full re-forwards, including the text-completion mode genDALLE
    exercises by passing a short unpadded prompt (reference genDALLE.py:106).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from dalle_pytorch_tpu.models import vae as vae_mod
from dalle_pytorch_tpu.ops import core, decode as decode_ops
from dalle_pytorch_tpu.ops import transformer as T

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class DALLEConfig:
    dim: int
    depth: int
    vae: vae_mod.VAEConfig
    num_text_tokens: int = 10000
    text_seq_len: int = 256
    heads: int = 8
    dim_head: int = 64
    reversible: bool = False
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    sparse_attn: Union[bool, Tuple[bool, ...]] = False
    sparse_block: int = 16
    attn_impl: str = "xla"
    # flash backward: 'xla' | 'pallas' (split) | 'pallas_fused' kernels
    attn_bwd_impl: str = "xla"
    flash_block_q: int = 128     # flash kernel tile sizes (transformer cfg)
    flash_block_k: int = 128
    sparse_impl: str = "ref"
    # MoE FF (beyond reference): 0 = plain GEGLU; >0 experts per layer,
    # expert axis shardable over 'ep'. aux coef weights the Switch
    # load-balance loss into the training objective.
    moe_experts: int = 0
    moe_k: int = 2
    moe_aux_coef: float = 1e-2
    scale_mode: str = "dim"     # reference transformer.py:57 uses dim**-0.5
    remat: str = "none"
    # 'grid' factorizes over the token grid; 'full_image' reproduces the
    # reference's (image_size, image_size) table quirk.
    axial_compat: str = "grid"
    # CE memory strategy: 0 computes the loss over the full (b, seq,
    # total_tokens) logits; a positive value streams the head+CE over
    # sequence chunks of that size under jax.checkpoint, so peak logits
    # memory is (b, chunk, total_tokens) — the 12k-vocab head over seq 1280
    # is otherwise the largest train-time buffer. Same loss, bitwise-close
    # grads; one extra head matmul on the backward pass.
    loss_chunk: int = 0

    @property
    def image_seq_len(self) -> int:
        return self.vae.image_seq_len

    @property
    def num_image_tokens(self) -> int:
        return self.vae.num_tokens

    @property
    def seq_len(self) -> int:
        return self.text_seq_len + self.image_seq_len

    @property
    def total_tokens(self) -> int:
        return self.num_text_tokens + self.num_image_tokens + 1  # + EOS

    @property
    def eos_token_id(self) -> int:
        return self.total_tokens - 1

    @property
    def transformer(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            dim=self.dim, depth=self.depth, seq_len=self.seq_len,
            heads=self.heads, dim_head=self.dim_head, causal=True,
            attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
            reversible=self.reversible, sparse_attn=self.sparse_attn,
            sparse_block=self.sparse_block, attn_impl=self.attn_impl,
            attn_bwd_impl=self.attn_bwd_impl,
            flash_block_q=self.flash_block_q,
            flash_block_k=self.flash_block_k,
            sparse_impl=self.sparse_impl, scale_mode=self.scale_mode,
            remat=self.remat, moe_experts=self.moe_experts,
            moe_k=self.moe_k)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def dalle_init(key: Array, cfg: DALLEConfig,
               vae_params: Optional[dict] = None,
               dtype=jnp.float32) -> dict:
    """Parameter pytree. ``vae_params`` seeds the tied image embedding from
    the VAE codebook (reference dalle_pytorch.py:283; requires
    vae.codebook_dim == dim, as the tie implies)."""
    ks = jax.random.split(key, 6)
    g = cfg.vae.grid_size

    if cfg.axial_compat == "full_image":
        ax_rows, ax_cols = cfg.vae.image_size, cfg.vae.image_size
    elif cfg.axial_compat == "grid":
        ax_rows, ax_cols = g, g
    else:
        raise ValueError(f"unknown axial_compat {cfg.axial_compat!r}")

    if vae_params is not None:
        if cfg.vae.codebook_dim != cfg.dim:
            raise ValueError(
                "tied codebook requires vae.codebook_dim == dalle dim "
                f"({cfg.vae.codebook_dim} != {cfg.dim})")
        image_emb = {"w": vae_params["codebook"]["w"].astype(dtype)}
    else:
        image_emb = core.embedding_init(ks[1], cfg.num_image_tokens, cfg.dim,
                                        dtype)

    return {
        "text_emb": core.embedding_init(ks[0], cfg.num_text_tokens, cfg.dim,
                                        dtype),
        "image_emb": image_emb,
        "text_pos_emb": core.embedding_init(ks[2], cfg.text_seq_len, cfg.dim,
                                            dtype),
        "image_pos_emb": {
            "rows": core.normal_init(ks[3], (ax_rows, cfg.dim), 1.0, dtype),
            "cols": core.normal_init(ks[4], (ax_cols, cfg.dim), 1.0, dtype),
        },
        "transformer": T.transformer_init(ks[5], cfg.transformer, dtype),
        "to_logits": {
            "ln": core.layernorm_init(cfg.dim, dtype),
            "proj": core.linear_init(jax.random.fold_in(ks[5], 1), cfg.dim,
                                     cfg.total_tokens, dtype=dtype),
        },
    }


# ---------------------------------------------------------------------------
# embeddings / masks
# ---------------------------------------------------------------------------

def image_pos_emb(params: dict, cfg: DALLEConfig, positions: Array) -> Array:
    """Summed-axial position embedding for flat image positions
    (0..image_seq_len). 'grid' maps n -> (n // g, n % g); 'full_image' maps
    over the image_size-wide table exactly as the reference's
    AxialPositionalEmbedding(axial_shape=(image_size, image_size)) does."""
    p = params["image_pos_emb"]
    width = p["cols"].shape[0]
    rows = jnp.take(p["rows"], positions // width, axis=0)
    cols = jnp.take(p["cols"], positions % width, axis=0)
    return rows + cols


def logits_mask(cfg: DALLEConfig) -> Array:
    """(seq_len, total_tokens) bool, True = FORBIDDEN (fill with -max), the
    reference's buffer (dalle_pytorch.py:303-315)."""
    n, t = cfg.seq_len, cfg.total_tokens
    seq = jnp.arange(n)[:, None]
    logit = jnp.arange(t)[None, :]
    text_boundary = cfg.text_seq_len - 1
    forbidden = (
        ((seq >= text_boundary) & (logit < cfg.num_text_tokens))
        | ((seq < text_boundary) & (logit >= cfg.num_text_tokens))
        | ((seq != (n - 1)) & (logit >= (t - 1)))
    )
    return forbidden


def embed_prompt(params: dict, cfg: DALLEConfig, text: Array,
                 image_ids: Optional[Array] = None) -> Array:
    """Token embeddings for [text (b, t)] ++ [image ids (b, n_img)]."""
    b, t = text.shape
    tok = (jnp.take(params["text_emb"]["w"], text, axis=0)
           + params["text_pos_emb"]["w"][None, :t])
    if image_ids is not None and image_ids.shape[1] > 0:
        n_img = image_ids.shape[1]
        img = (jnp.take(params["image_emb"]["w"], image_ids, axis=0)
               + image_pos_emb(params, cfg, jnp.arange(n_img))[None])
        tok = jnp.concatenate([tok, img], axis=1)
    return tok


def decode_token_embed(params: dict, cfg: DALLEConfig, cur_tok: Array,
                       pos: Array) -> Array:
    """Embedding of the token(s) fed at position(s) ``pos`` during KV-cache
    decoding — the ONE definition shared by ``generate_images``'s scan and
    the serve engine's slot-batched step (serve/engine.py), so the two
    samplers cannot diverge. ``cur_tok`` (b,) ids (image ids WITHOUT the
    text-vocab offset); ``pos`` a traced scalar or a (b,) per-slot vector.
    Ids are clipped into each table so the off-branch gather of the
    ``where`` select stays in range."""
    pos = jnp.asarray(pos)
    text_e = (jnp.take(params["text_emb"]["w"],
                       jnp.clip(cur_tok, 0, cfg.num_text_tokens - 1),
                       axis=0)
              + jnp.take(params["text_pos_emb"]["w"],
                         jnp.clip(pos, 0, cfg.text_seq_len - 1), axis=0))
    img_pos = jnp.clip(pos - cfg.text_seq_len, 0, cfg.image_seq_len - 1)
    img_e = (jnp.take(params["image_emb"]["w"],
                      jnp.clip(cur_tok, 0, cfg.num_image_tokens - 1),
                      axis=0)
             + image_pos_emb(params, cfg, img_pos))
    is_text = pos < cfg.text_seq_len
    if pos.ndim:
        is_text = is_text[:, None]
    return jnp.where(is_text, text_e, img_e)


def to_logits(params: dict, h: Array) -> Array:
    h = core.layernorm(params["to_logits"]["ln"], h)
    return core.linear(params["to_logits"]["proj"], h)


def draft_transformer_config(tcfg: T.TransformerConfig,
                             d: int) -> T.TransformerConfig:
    """The shallow draft model's config for speculative decode: the
    first ``d`` layers of the target transformer, everything else
    unchanged. ``sparse_attn`` must be re-sliced explicitly because
    ``sparse_pattern`` is derived from depth — a bare depth override
    would re-broadcast a bool or fail the tuple-length assert."""
    if not 1 <= d <= tcfg.depth:
        raise ValueError(
            f"draft depth must be in [1, {tcfg.depth}], got {d}")
    return dataclasses.replace(
        tcfg, depth=d, sparse_attn=tuple(tcfg.sparse_pattern[:d]))


def draft_transformer_params(params: dict, d: int) -> dict:
    """The draft head's weights: the leading-``d`` slice of every
    stacked transformer leaf. An early exit, not a separate model — the
    draft shares the target's weights (and, at the call site, the SAME
    ``to_logits`` head and sampler), so no extra memory and no training.
    Cheap under jit (a slice of resident buffers, no copy); call it
    INSIDE the traced decode fn so hot-swapped weights stay live."""
    return jax.tree.map(lambda a: a[:d], params)


def quantize_for_decode(params: dict) -> dict:
    """Int8-quantize the weight-heavy inference path — the transformer
    linears and the vocab head (ops.quant docstring has the bandwidth
    arithmetic). Embedding tables, positional/axial tables, layernorms,
    and the tied codebook stay in their stored dtype: they are gathered
    or tiny, and the VAE decode needs the codebook as-is. Inference only
    (no tangent through int8); quantize after restore, never checkpoint
    the result."""
    from dalle_pytorch_tpu.ops import quant
    out = dict(params)
    out["transformer"] = quant.quantize_tree_int8(params["transformer"])
    out["to_logits"] = quant.quantize_tree_int8(params["to_logits"])
    return out


# ---------------------------------------------------------------------------
# forward / loss
# ---------------------------------------------------------------------------

def dalle_apply(params: dict, text: Array, image=None, *, cfg: DALLEConfig,
                mask: Optional[Array] = None,
                vae_params: Optional[dict] = None,
                rng: Optional[Array] = None, train: bool = False,
                return_loss: bool = False):
    """Forward (reference DALLE.forward, dalle_pytorch.py:360-407).

    ``image`` may be token ids (b, n_img) int, raw images (b, H, W, C) float
    (tokenized through the frozen VAE encoder, no gradient — reference
    :375-378 under @torch.no_grad), or None (text-only prefix).
    Returns logits (b, seq, total_tokens) or the scalar CE loss.
    """
    image_ids = None
    if image is not None:
        if image.ndim == 4:
            if vae_params is None:
                raise ValueError("raw images need vae_params to tokenize")
            image_ids = lax.stop_gradient(
                vae_mod.get_codebook_indices(vae_params, image))
        else:
            image_ids = image

    tokens = embed_prompt(params, cfg, text, image_ids)
    seq_len = tokens.shape[1]

    if mask is not None and image_ids is not None:
        pad = jnp.ones((mask.shape[0], image_ids.shape[1]), bool)
        mask = jnp.concatenate([mask, pad], axis=1)

    h, aux = T.transformer_apply(params["transformer"], tokens,
                                 cfg=cfg.transformer, mask=mask, rng=rng,
                                 train=train, with_aux=True)

    if not return_loss:
        logits = to_logits(params, h)
        forbidden = logits_mask(cfg)[:seq_len]
        return jnp.where(forbidden[None], core.neg_inf(logits.dtype), logits)

    if image_ids is None:
        raise ValueError("when training, image must be supplied")
    loss = ce_from_hidden(params, h, text, image_ids, cfg=cfg)
    if cfg.moe_experts:
        loss = loss + cfg.moe_aux_coef * aux
    return loss


def ce_from_hidden(params: dict, h: Array, text: Array, image_ids: Array, *,
                   cfg: DALLEConfig) -> Array:
    """The training-loss tail shared by every execution path (single-device
    ``dalle_apply`` and the sequence-parallel loss in parallel.sequence):
    labels = [text, image+offset] shifted left with EOS appended, masked
    logits, mean CE (reference dalle_pytorch.py:391-406). Honors
    ``cfg.loss_chunk`` (streamed head)."""
    labels = jnp.concatenate(
        [text, image_ids + cfg.num_text_tokens,
         jnp.full((text.shape[0], 1), cfg.eos_token_id, text.dtype)], axis=1)
    targets = labels[:, 1:]                      # predict token i+1 at row i

    if cfg.loss_chunk > 0:
        return _chunked_ce(params, h, targets, cfg)
    logits = to_logits(params, h)
    forbidden = logits_mask(cfg)[:h.shape[1]]
    logits = jnp.where(forbidden[None], core.neg_inf(logits.dtype), logits)
    return jnp.mean(_nll(logits, targets))


def _nll(logits: Array, targets: Array) -> Array:
    """-log_softmax(logits)[targets] as logsumexp - gathered logit: same
    math, but the full-vocab f32 log-probability tensor (the largest buffer
    in the dense CE head — (b, 1280, 12k) f32 at bench shape) never
    materializes; only the (b, n) reductions do."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None],
                              axis=-1)[..., 0].astype(jnp.float32)
    return lse - tgt


def _chunked_ce(params: dict, h: Array, targets: Array,
                cfg: DALLEConfig) -> Array:
    """Streamed head + cross-entropy: identical math to the dense path, but
    the (chunk, total_tokens) logits exist only inside a rematerialized scan
    body, so the full (b, seq, total_tokens) tensor is never resident.

    The forbidden-position mask participates BEFORE the log_softmax (it
    shapes the partition function, reference dalle_pytorch.py:391-396), so
    it is applied per chunk, not folded into the gather."""
    b, n, d = h.shape
    chunk = min(cfg.loss_chunk, n)
    pad = (-n) % chunk
    valid = jnp.ones((n,), jnp.float32)
    forbidden = logits_mask(cfg)[:n]
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, (0, pad))
        forbidden = jnp.pad(forbidden, ((0, pad), (0, 0)))
    steps = (n + pad) // chunk

    h_c = jnp.moveaxis(h.reshape(b, steps, chunk, d), 1, 0)
    t_c = jnp.moveaxis(targets.reshape(b, steps, chunk), 1, 0)
    f_c = forbidden.reshape(steps, chunk, -1)
    v_c = valid.reshape(steps, chunk)

    def body(acc, xs):
        hc, tc, fc, vc = xs
        logits = to_logits(params, hc)
        logits = jnp.where(fc[None], core.neg_inf(logits.dtype), logits)
        return acc + jnp.sum(_nll(logits, tc) * vc[None]), None

    total, _ = lax.scan(jax.checkpoint(body), jnp.float32(0.0),
                        (h_c, t_c, f_c, v_c))
    return total / (b * n)


# ---------------------------------------------------------------------------
# generation — jit lax.scan sampler with KV cache
# ---------------------------------------------------------------------------

def top_k_filter(logits: Array, thres: float) -> Array:
    """Keep the top (1-thres)·vocab logits, -inf the rest (reference
    top_k helper, dalle_pytorch.py:41-47)."""
    k = max(int((1 - thres) * logits.shape[-1]), 1)
    kth = lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, core.neg_inf(logits.dtype), logits)


def top_p_filter(logits: Array, p: float) -> Array:
    """Nucleus filter (beyond reference — the reference samples top-k
    only, dalle_pytorch.py:41-47): keep the smallest prefix of
    descending-probability tokens whose cumulative mass reaches ``p``,
    -inf the rest. Static-shaped (sort + cumsum), so it jits into the
    same one-program sampler as the top-k path. Callers must pass
    TEMPERATURE-SCALED logits: the nucleus is defined on the actual
    sampling distribution."""
    if not 0.0 < p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {p}")
    sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a token is kept when the mass BEFORE it is still < p, so the argmax
    # always survives; masked (-inf) tokens carry zero mass and sit at
    # cum == 1, never kept for p <= 1
    keep_sorted = (cum - probs) < p
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits,
                               jnp.inf).astype(logits.dtype),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, core.neg_inf(logits.dtype), logits)


def sample_per_slot(logits: Array, pred_pos: Array, keys: Array,
                    temp: Array, topk_k: Array, top_p: Array,
                    cfg: DALLEConfig, *,
                    partner: Optional[Array] = None,
                    cfg_scale: Optional[Array] = None,
                    uncond: Optional[Array] = None) -> Array:
    """Per-slot sampling: the traced-parameter form of ``generate_images``'s
    ``sample`` — forbidden-position mask, temperature, top-k OR nucleus
    filter, categorical — with every knob a (slots,) array instead of a
    python constant, so the serve engine's one compiled program covers any
    per-request mix (serve/engine.py holds the equivalence contract).

    Value-identical to the one-shot path per slot: the top-k threshold is
    the k-th largest logit (what ``lax.top_k(...)[..., -1:]`` returns)
    read off a full descending sort so k can vary per slot; the nucleus
    branch is ``top_p_filter``'s exact math with p broadcast per slot.
    Both filters are computed every step (fixed shape) and selected per
    slot; ``top_p > 0`` selects nucleus, exactly as the python-level
    branch does in ``generate_images``. Per-slot draws go through
    ``fold_in(key, pred_pos)`` — the one-shot sampler's key discipline —
    and ``jax.random.categorical`` over one slot's (vocab,) row equals
    the batch-1 call with the same key. Returns sampled ids with the
    text-vocab offset removed for image positions, as ``generate_images``
    stores them.

    ``partner``/``cfg_scale``/``uncond`` (all (slots,); pass together or
    not at all) fold per-request classifier-free guidance into the SAME
    program: a guided request occupies a cond/uncond slot pair (each the
    other's ``partner``; self elsewhere), and a cond slot with
    ``cfg_scale > 0`` samples image positions from
    ``l_uncond + cfg_scale * (l_cond - l_uncond)`` — the identical
    formula, f32 mix, and cast of ``generate_images``' guided ``sample``
    — while its uncond partner takes the cond slot's drawn token (the
    one-shot path's ``tile``), so the pair's caches stay in step. Text
    positions sample from the cond stream alone, exactly as one-shot."""
    forbidden = logits_mask(cfg)
    lg = jnp.where(jnp.take(forbidden, pred_pos - 1, axis=0),
                   core.neg_inf(logits.dtype), logits)
    if partner is not None:
        # guided mix BEFORE temperature, on the masked logits — the
        # one-shot ``sample``'s order. f32: the forbidden fill is
        # -finfo.max and the extrapolation must not overflow it.
        l_self = lg.astype(jnp.float32)
        l_pair = jnp.take(lg, partner, axis=0).astype(jnp.float32)
        # on a cond slot the partner IS the uncond stream: the mix
        # below is literally l_u + scale * (l_c - l_u)
        mix = (l_pair + cfg_scale[:, None] * (l_self - l_pair)) \
            .astype(lg.dtype)
        guided_img = ((cfg_scale > 0) & ~uncond
                      & (pred_pos >= cfg.text_seq_len))
        lg = jnp.where(guided_img[:, None], mix, lg)
    lg = lg / temp[:, None]

    sorted_desc = jnp.flip(jnp.sort(lg, axis=-1), axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (topk_k - 1)[:, None], axis=-1)
    by_k = jnp.where(lg < kth, core.neg_inf(lg.dtype), lg)

    probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None]
    thresh = jnp.min(jnp.where(keep_sorted, sorted_desc,
                               jnp.inf).astype(lg.dtype),
                     axis=-1, keepdims=True)
    by_p = jnp.where(lg < thresh, core.neg_inf(lg.dtype), lg)

    lg = jnp.where((top_p > 0)[:, None], by_p, by_k)
    folded = jax.vmap(jax.random.fold_in)(keys, pred_pos)
    raw = jax.vmap(jax.random.categorical)(folded, lg)
    if partner is not None:
        # the uncond slot takes its cond partner's drawn token — the
        # one-shot guided path's ``tile(raw, 2)``: both streams of a
        # pair consume the same token so their KV caches agree
        raw = jnp.where((cfg_scale > 0) & uncond,
                        jnp.take(raw, partner), raw)
    is_image = pred_pos >= cfg.text_seq_len
    return jnp.where(is_image, raw - cfg.num_text_tokens, raw)


def generate_images(params: dict, vae_params: dict, text: Array, *,
                    cfg: DALLEConfig, rng: Array,
                    mask: Optional[Array] = None,
                    filter_thres: float = 0.5,
                    top_p: float = 0.0,
                    temperature: float = 1.0,
                    guidance: float = 0.0,
                    clip_params: Optional[dict] = None,
                    clip_cfg=None,
                    return_img_seq: bool = False,
                    quantize_cache: bool = False):
    """Sample image tokens autoregressively, decode through the VAE.

    Matches the reference sampling distribution (reference
    dalle_pytorch.py:317-358): per step the masked logits are top-k filtered
    (keep top half by default) and sampled at ``temperature``; prompts
    shorter than text_seq_len are completed through the text span first
    (genDALLE's unpadded-prompt mode). With ``clip_params`` the generated
    images are scored by CLIP (reference :354-356).

    ``guidance`` > 0 enables classifier-free guidance (beyond reference):
    a second, unconditional stream — the all-PAD null caption — rides in
    the batch dimension of the SAME one-program scan, and each image
    token samples from ``l_uncond + guidance * (l_cond - l_uncond)``
    (guidance 1.0 reduces to conditional sampling). Both streams consume
    the same sampled image tokens so their KV caches agree; text
    positions sample from the conditional stream alone while the null
    stream keeps PAD. Train with ``--caption_drop`` so the model has
    seen null captions.

    ``quantize_cache`` stores the KV cache int8 with per-row scales
    (ops.decode.init_cache) — halves the cache's share of per-token HBM
    reads (bench.decode_roofline_ms_per_token quantifies it; the term
    dominates at batch > 1). Composes with ``quantize_for_decode``
    (int8 weights) for the full int8 decode path, and with the serving
    engine's PAGED KV layout (serve/kv_pool.py): the int8 page pool
    carries the same per-row scales per page, quantizes through the
    same ``_quantize_rows``, and obeys the identical error contract —
    int8 halves the bytes per page exactly as it halves them per dense
    row, so the two HBM levers multiply. Accuracy: the int8
    rows plus the scale-cast-to-score-dtype under bf16 compound to a
    ~1% relative attention-output error bound per layer (see
    ops.decode.init_cache); tests/test_quant.py's 2% end-to-end parity
    tolerance is that contract. There is no opt-out short of
    ``quantize_cache=False``.
    """
    if clip_params is not None and \
            clip_cfg.num_text_tokens < cfg.num_text_tokens:
        # an undersized CLIP vocab would make the rerank's embedding
        # gather go out of range on sampled text ids — which jnp.take
        # (default mode='fill') turns into NaN latents and NaN scores
        # with no error. Fail before the expensive sampling scan instead
        # (config-only check, so eager callers fail fast too).
        raise ValueError(
            f"CLIP num_text_tokens ({clip_cfg.num_text_tokens}) < "
            f"DALLE num_text_tokens ({cfg.num_text_tokens}): the "
            "rerank would gather out-of-range text ids (NaN scores); "
            "train CLIP with a vocab covering the DALLE's")
    b, t0 = text.shape
    total_len = cfg.seq_len
    tcfg = cfg.transformer

    guided = guidance > 0
    if guided:
        # unconditional stream = the all-PAD null caption, batched below
        # the conditional rows so one scan serves both
        text = jnp.concatenate([text, jnp.zeros_like(text)], axis=0)
        if mask is not None:
            # the null stream gets an all-True mask: --caption_drop
            # training attends every PAD position of a dropped caption
            # (loss_fn's all-True mask), and the uncond baseline must
            # match that distribution
            mask = jnp.concatenate([mask, jnp.ones_like(mask)], axis=0)
    rows = text.shape[0]

    tokens = embed_prompt(params, cfg, text)
    h, cache = decode_ops.prefill(params["transformer"], tokens, cfg=tcfg,
                                  total_len=total_len, prompt_mask=mask,
                                  quantize_cache=quantize_cache)
    key_mask = decode_ops._full_key_mask(mask, rows, t0, total_len)
    forbidden = logits_mask(cfg)
    uncond_rows = jnp.arange(rows) >= b

    def sample(logits_row, pred_pos, key):
        """Sample the token for position pred_pos from last-row logits."""
        lg = jnp.where(forbidden[pred_pos - 1][None], core.neg_inf(
            logits_row.dtype), logits_row)
        is_image = pred_pos >= cfg.text_seq_len
        if guided:
            # mix in f32: the forbidden fill is -finfo.max and the
            # extrapolation below must not overflow it
            l_c = lg[:b].astype(jnp.float32)
            l_u = lg[b:].astype(jnp.float32)
            mix = l_u + guidance * (l_c - l_u)
            lg = jnp.where(is_image, mix, l_c).astype(lg.dtype)
        # temperature first: the nucleus must hold p mass of the ACTUAL
        # sampling distribution (top-k is rank-preserving, so the reorder
        # is behavior-neutral for the reference path). Static python
        # branch: top_p > 0 selects nucleus, else reference top-k.
        lg = lg / temperature
        lg = (top_p_filter(lg, top_p) if top_p > 0
              else top_k_filter(lg, filter_thres))
        raw = jax.random.categorical(key, lg, axis=-1)
        if guided:
            raw = jnp.tile(raw, 2)       # both streams take the same token
        return jnp.where(is_image, raw - cfg.num_text_tokens, raw)

    # token for position t0 from the prefill's last row
    first_tok = sample(to_logits(params, h[:, -1]), t0,
                       jax.random.fold_in(rng, t0))

    def step(carry, pos):
        cur_tok, cache = carry
        is_text = pos < cfg.text_seq_len
        if guided:
            # the null stream's text stays PAD — feeding it the sampled
            # caption would make it conditional
            cur_tok = jnp.where(is_text & uncond_rows, 0, cur_tok)
        x = decode_token_embed(params, cfg, cur_tok, pos)

        h_tok, cache = decode_ops.decode_step(params["transformer"], x, pos,
                                              cache, cfg=tcfg,
                                              key_mask=key_mask)
        nxt = sample(to_logits(params, h_tok), pos + 1,
                     jax.random.fold_in(rng, pos + 1))
        return (nxt, cache), cur_tok

    positions = jnp.arange(t0, total_len)
    (_, _), toks = lax.scan(step, (first_tok, cache), positions)
    toks = jnp.moveaxis(toks, 0, 1)                  # (rows, total_len - t0)

    full = jnp.concatenate([text, toks], axis=1)[:b]   # cond stream only
    img_seq = full[:, -cfg.image_seq_len:]
    images = vae_mod.decode(vae_params, img_seq,
                            codebook=params["image_emb"]["w"])

    if return_img_seq:
        return images, img_seq
    if clip_params is not None:
        from dalle_pytorch_tpu.models import clip as clip_mod
        text_seq = full[:, :cfg.text_seq_len]
        scores = clip_mod.clip_apply(clip_params, text_seq, images,
                                     cfg=clip_cfg)
        return images, scores
    return images


# ---------------------------------------------------------------------------
# OO wrapper for reference-API parity
# ---------------------------------------------------------------------------

class DALLE:
    """Reference-shaped facade (reference dalle_pytorch.py:241-407) over the
    functional core. Holds its own params plus the VAE it tokenizes/decodes
    through."""

    def __init__(self, *, dim: int, vae: vae_mod.DiscreteVAE, depth: int,
                 key: Optional[Array] = None, params: Optional[dict] = None,
                 dtype=jnp.float32, **cfg_kwargs):
        if not isinstance(vae, vae_mod.DiscreteVAE):
            raise TypeError("vae must be a DiscreteVAE")
        self.vae = vae
        self.config = DALLEConfig(dim=dim, depth=depth, vae=vae.config,
                                  **cfg_kwargs)
        if params is None:
            if key is None:
                key = jax.random.PRNGKey(0)
            params = dalle_init(key, self.config, vae.params, dtype)
        self.params = params

    def __call__(self, text: Array, image=None, mask: Optional[Array] = None,
                 return_loss: bool = False, rng: Optional[Array] = None,
                 train: bool = False):
        return dalle_apply(self.params, text, image, cfg=self.config,
                           mask=mask, vae_params=self.vae.params, rng=rng,
                           train=train, return_loss=return_loss)

    forward = __call__

    def generate_images(self, text: Array, *, rng: Optional[Array] = None,
                        clip=None, mask: Optional[Array] = None,
                        filter_thres: float = 0.5, top_p: float = 0.0,
                        guidance: float = 0.0, temperature: float = 1.0):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        kwargs = {}
        if clip is not None:
            kwargs = {"clip_params": clip.params, "clip_cfg": clip.config}
        return generate_images(self.params, self.vae.params, text,
                               cfg=self.config, rng=rng, mask=mask,
                               filter_thres=filter_thres, top_p=top_p,
                               guidance=guidance,
                               temperature=temperature, **kwargs)
