"""CLIP — dual-encoder contrastive model, TPU-native.

Capability parity with the reference CLIP (reference
dalle_pytorch/dalle_pytorch.py:161-237): a text transformer and a ViT-style
patch transformer pooled to L2-normalized latents, a learned temperature
(stored pre-exp), paired similarities at inference, and one-directional
(text→image) InfoNCE at training (reference :230-237). Used standalone or as
the reranker for DALLE.generate_images (reference :354-356).

Faithfulness notes:
  * both encoders are non-causal and — like the reference, which leaves the
    Transformer default ``sparse_attn=True`` (reference transformer.py:151)
    — default to block-sparse attention in the BIDIRECTIONAL layout; pass
    ``sparse_attn=False`` for dense;
  * text pooling is a mask-weighted mean when a pad mask is given
    (reference masked_mean, :26-28), plain mean otherwise; image pooling is
    a plain mean over patches;
  * images are NHWC (TPU layout); the patch flattening keeps the reference's
    (p1, p2, c) feature order so weights are interchangeable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.ops import core
from dalle_pytorch_tpu.ops import transformer as T

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    dim_text: int = 512
    dim_image: int = 512
    dim_latent: int = 512
    num_text_tokens: int = 10000
    text_enc_depth: int = 6
    text_seq_len: int = 256
    text_heads: int = 8
    num_visual_tokens: int = 512
    visual_enc_depth: int = 6
    visual_heads: int = 8
    visual_image_size: int = 256
    visual_patch_size: int = 32
    channels: int = 3
    sparse_attn: bool = True     # the reference Transformer default
    sparse_block: int = 16
    sparse_impl: str = "ref"

    def __post_init__(self):
        if self.visual_image_size % self.visual_patch_size != 0:
            raise ValueError(
                "image dimensions must be divisible by the patch size")

    @property
    def num_patches(self) -> int:
        return (self.visual_image_size // self.visual_patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.visual_patch_size ** 2

    def _enc(self, dim, depth, heads, seq_len) -> T.TransformerConfig:
        return T.TransformerConfig(
            dim=dim, depth=depth, seq_len=seq_len, heads=heads, dim_head=64,
            causal=False, sparse_attn=self.sparse_attn,
            sparse_block=self.sparse_block, sparse_impl=self.sparse_impl)

    @property
    def text_transformer(self) -> T.TransformerConfig:
        return self._enc(self.dim_text, self.text_enc_depth, self.text_heads,
                         self.text_seq_len)

    @property
    def visual_transformer(self) -> T.TransformerConfig:
        return self._enc(self.dim_image, self.visual_enc_depth,
                         self.visual_heads, self.num_patches)


def clip_init(key: Array, cfg: CLIPConfig, dtype=jnp.float32) -> dict:
    ks = jax.random.split(key, 8)
    return {
        "text_emb": core.embedding_init(ks[0], cfg.num_text_tokens,
                                        cfg.dim_text, dtype),
        "text_pos_emb": core.embedding_init(ks[1], cfg.text_seq_len,
                                            cfg.dim_text, dtype),
        "text_transformer": T.transformer_init(ks[2], cfg.text_transformer,
                                               dtype),
        "to_text_latent": core.linear_init(ks[3], cfg.dim_text,
                                           cfg.dim_latent, bias=False,
                                           dtype=dtype),
        "to_visual_emb": core.linear_init(ks[4], cfg.patch_dim, cfg.dim_image,
                                          dtype=dtype),
        "visual_pos_emb": core.embedding_init(ks[5], cfg.num_patches,
                                              cfg.dim_image, dtype),
        "visual_transformer": T.transformer_init(
            ks[6], cfg.visual_transformer, dtype),
        "to_visual_latent": core.linear_init(ks[7], cfg.dim_image,
                                             cfg.dim_latent, bias=False,
                                             dtype=dtype),
        # stored pre-exp, init 1.0 (reference :195,228)
        "temperature": jnp.ones((), dtype),
    }


def patchify(images: Array, patch: int) -> Array:
    """(b, H, W, C) -> (b, num_patches, p*p*C) with (p1, p2, c) feature
    order (reference rearrange, dalle_pytorch.py:209)."""
    b, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(b, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)           # b, gh, gw, p1, p2, c
    return x.reshape(b, gh * gw, patch * patch * C)


def masked_mean(t: Array, mask: Array) -> Array:
    """Mean over axis 1 counting only mask=True rows (reference :26-28)."""
    t = jnp.where(mask[:, :, None], t, 0.0)
    return t.sum(axis=1) / mask.sum(axis=1)[:, None]


def encode_text(params: dict, text: Array, cfg: CLIPConfig,
                mask: Optional[Array] = None) -> Array:
    x = (jnp.take(params["text_emb"]["w"], text, axis=0)
         + params["text_pos_emb"]["w"][None, :text.shape[1]])
    enc = T.transformer_apply(params["text_transformer"], x,
                              cfg=cfg.text_transformer, mask=mask)
    pooled = masked_mean(enc, mask) if mask is not None else enc.mean(axis=1)
    lat = core.linear(params["to_text_latent"], pooled)
    return lat / jnp.linalg.norm(lat, axis=-1, keepdims=True)


def encode_image(params: dict, images: Array, cfg: CLIPConfig) -> Array:
    patches = patchify(images, cfg.visual_patch_size)
    x = core.linear(params["to_visual_emb"], patches)
    x = x + params["visual_pos_emb"]["w"][None]
    enc = T.transformer_apply(params["visual_transformer"], x,
                              cfg=cfg.visual_transformer)
    lat = core.linear(params["to_visual_latent"], enc.mean(axis=1))
    return lat / jnp.linalg.norm(lat, axis=-1, keepdims=True)


def clip_apply(params: dict, text: Array, images: Array, *, cfg: CLIPConfig,
               text_mask: Optional[Array] = None,
               return_loss: bool = False):
    """Paired similarity scores (b,) or, with ``return_loss``, the
    one-directional InfoNCE loss over the in-batch sim matrix
    (reference :228-237)."""
    tl = encode_text(params, text, cfg, text_mask)
    il = encode_image(params, images, cfg)
    temp = jnp.exp(params["temperature"])

    if not return_loss:
        return jnp.einsum("nd,nd->n", tl, il) * temp

    sim = jnp.einsum("id,jd->ij", tl, il) * temp
    labels = jnp.arange(sim.shape[0])
    logp = jax.nn.log_softmax(sim.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


class CLIP:
    """Reference-shaped facade (reference dalle_pytorch.py:161-237)."""

    def __init__(self, key: Optional[Array] = None, *,
                 params: Optional[dict] = None, dtype=jnp.float32,
                 **cfg_kwargs):
        self.config = CLIPConfig(**cfg_kwargs)
        if params is None:
            if key is None:
                key = jax.random.PRNGKey(0)
            params = clip_init(key, self.config, dtype)
        self.params = params

    def __call__(self, text: Array, images: Array,
                 text_mask: Optional[Array] = None,
                 return_loss: bool = False):
        return clip_apply(self.params, text, images, cfg=self.config,
                          text_mask=text_mask, return_loss=return_loss)

    forward = __call__
