"""Profiling hooks: jax.profiler trace capture around train steps.

SURVEY.md §5.1 — the reference has no profiler at all; the TPU build exposes
XLA's own tracer so a Perfetto/TensorBoard trace of the compiled train step
(matmul tiling, collective overlap, host gaps) is one flag away in every CLI
(``--profile_dir``).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str], *, first_step: int = 0,
          num_steps: int = 3) -> Iterator[None]:
    """No-op when ``log_dir`` is falsy; otherwise captures a jax.profiler
    trace (viewable in TensorBoard / Perfetto). Wrap the steady-state steps,
    not step 0 — compile time would swamp the trace."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


class StepProfiler:
    """Captures a trace window [start, start+steps) inside a training loop:

        prof = StepProfiler(log_dir, start=10, steps=3)
        for i, batch in ...:
            prof.maybe_start(i)
            ...train step...
            prof.maybe_stop(i)
    """

    def __init__(self, log_dir: Optional[str], start: int = 10,
                 steps: int = 3):
        self.log_dir = log_dir
        self.start = start
        self.stop_at = start + steps
        self._active = False

    def maybe_start(self, step: int) -> None:
        if self.log_dir and not self._active and step == self.start:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def maybe_stop(self, step: int) -> None:
        if self._active and step + 1 >= self.stop_at:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
