"""Training metrics: throughput counters + JSONL logging.

The reference's observability is bare ``print`` of per-interval batch loss
and per-epoch averages (reference trainVAE.py:98-102,116-117,
trainDALLE.py:201-210). SURVEY.md §5.5 asks the rebuild for real counters —
tokens/sec/chip is the north-star metric, so the training CLIs log it per
interval, not just in bench.py.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

# NOTE: jax is imported lazily inside MetricsLogger — ``structured_event``
# must be importable before any backend exists (resilience.retry emits
# bring-up failure records from bench.py's pre-claim main thread, where a
# jax import must stay inside the deadline-bounded claim thread).


def structured_event(kind: str, **fields) -> dict:
    """The canonical resilience-event record: every failure/retry/rollback/
    preempt/resume event in the system is one of these, so benches and
    VERDICT can distinguish "stale because wedged" from "retried and
    recovered" by grepping one shape. ``kind`` ∈ {bringup_retry,
    bringup_failure, rollback, diverged, step_checkpoint, preempt_signal,
    preempted, resume, prefetch_bad_record, prefetch_restart, ...}."""
    # jaxlint: disable=JL007 — epoch timestamp in the event record, not
    # duration math (durations here always come from perf_counter deltas)
    return {"time": time.time(), "event": "resilience", "kind": kind,
            **fields}


class MetricsLogger:
    """Per-step metrics with wall-clock throughput, echoed to stdout and
    appended as JSONL (one object per log call) for post-hoc analysis."""

    def __init__(self, path: Optional[str] = None, log_interval: int = 10,
                 n_devices: Optional[int] = None):
        """``n_devices`` is the number of chips actually participating in
        the training mesh (NOT all local devices — a --dp subset must not
        deflate the per-chip rate). Defaults to jax.device_count()."""
        # multi-host: only process 0 prints and writes the JSONL (every
        # host sees the same replicated loss; racing appends interleave)
        import jax
        from dalle_pytorch_tpu.parallel.multihost import is_primary
        self.primary = is_primary()
        # the train loops feed host-LOCAL units; per-host work is equalized
        # by data.shard_for_host, so the global rate is local_rate × hosts
        self.process_count = jax.process_count()
        self.path = path if self.primary else None
        self.log_interval = log_interval
        self.n_devices = n_devices
        self._t_last = time.perf_counter()
        self._units_since = 0
        # one persistent handle behind one lock: the serving stack's
        # threads (engine loops, postprocess, supervisors, autoscaler)
        # all append structured events concurrently, and the old
        # per-call open(..., "a") raced them — two interleaved
        # buffered writes could tear a JSONL line. Flush per record
        # keeps the file current for live tail readers.
        self._lock = threading.Lock()
        self._fh = None
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)

    def _write(self, rec: dict) -> None:
        if not self.path:
            return
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def step(self, step: int, loss: float, *, epoch: Optional[int] = None,
             units: int = 0, unit_name: str = "tokens", **extra) -> None:
        """Call once per train step; prints/writes every ``log_interval``.
        ``units`` is the work done this step (tokens, images...)."""
        self._units_since += units
        if step % self.log_interval != 0:
            return
        import jax
        now = time.perf_counter()
        dt = max(now - self._t_last, 1e-9)
        rate = self._units_since / dt
        # rate is host-local, so the default denominator must be too —
        # jax.device_count() would understate per-chip by process_count
        n_dev = max(self.n_devices or jax.local_device_count(), 1)
        rec = {
            "step": step, "loss": float(loss),
            f"{unit_name}_per_sec": round(rate * self.process_count, 2),
            f"{unit_name}_per_sec_per_chip": round(rate / n_dev, 2),
            "time": time.time(),  # jaxlint: disable=JL007 — epoch stamp
        }
        if epoch is not None:
            rec["epoch"] = epoch
        rec.update(extra)
        self._t_last = now
        self._units_since = 0
        head = f"epoch {epoch} " if epoch is not None else ""
        if self.primary:
            print(f"{head}step {step}  loss {rec['loss']:.6f}  "
                  f"{rec[f'{unit_name}_per_sec_per_chip']:.1f} "
                  f"{unit_name}/s/chip", flush=True)
        self._write(rec)

    def event(self, **fields) -> None:
        """Free-form record (epoch summaries, checkpoint writes...)."""
        rec = {"time": time.time(), **fields}  # jaxlint: disable=JL007 — epoch stamp
        self._write(rec)

    def resilience(self, kind: str, **fields) -> None:
        """Structured failure/retry/rollback record — echoed to stdout
        (these are the events an operator must see even without a JSONL
        sink) and appended like any other event."""
        rec = structured_event(kind, **fields)
        if self.primary:
            detail = {k: v for k, v in rec.items()
                      if k not in ("time", "event")}
            print(f"[resilience] {json.dumps(detail)}", flush=True)
        self._write(rec)
