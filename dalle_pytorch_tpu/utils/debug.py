"""Debug toggles: NaN checking and loss-sanity guards.

SURVEY.md §5.2 — the reference has no sanitizers; JAX's own are one config
flag away. ``enable_nan_checks`` flips jax_debug_nans/infs (every jit op
re-checked — slow, debugging only). ``check_finite_tree``/``guard_loss`` are
the cheap always-on variants the CLIs use to fail fast with context instead
of training on garbage.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def enable_nan_checks(enable: bool = True) -> None:
    """Global jax NaN/Inf trap — raises at the op that produced the first
    non-finite value (disables some fusions; use for debugging runs)."""
    jax.config.update("jax_debug_nans", enable)
    jax.config.update("jax_debug_infs", enable)


def check_finite_tree(tree: Any, name: str = "tree") -> None:
    """Host-side assert that every leaf is finite (blocks on the values)."""
    bad = []

    def visit(path, leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            if not bool(jnp.isfinite(leaf).all()):
                bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(visit, tree)
    if bad:
        raise FloatingPointError(
            f"non-finite values in {name}: {', '.join(bad[:8])}"
            + (" ..." if len(bad) > 8 else ""))


def guard_loss(loss, step: int):
    """Raise with step context when the scalar loss goes non-finite."""
    val = float(loss)
    if not jnp.isfinite(val):
        raise FloatingPointError(f"loss became {val} at step {step}")
    return val
