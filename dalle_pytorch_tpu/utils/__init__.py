"""Aux subsystems: metrics, profiling, debug toggles (SURVEY.md §5.1/2/5)."""

from dalle_pytorch_tpu.utils.debug import (check_finite_tree,
                                           enable_nan_checks, guard_loss)
from dalle_pytorch_tpu.utils.metrics import MetricsLogger
from dalle_pytorch_tpu.utils.profiling import StepProfiler, trace

__all__ = ["MetricsLogger", "StepProfiler", "trace", "enable_nan_checks",
           "check_finite_tree", "guard_loss"]
