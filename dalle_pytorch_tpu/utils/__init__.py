"""Aux subsystems: metrics, profiling, debug toggles (SURVEY.md §5.1/2/5).

Lazy exports (mirroring the root package): ``utils.metrics`` must be
importable without jax — resilience.retry emits structured bring-up
failure records from bench.py's pre-claim main thread, where the jax
import stays inside the deadline-bounded claim thread.
"""

__all__ = ["MetricsLogger", "StepProfiler", "trace", "enable_nan_checks",
           "check_finite_tree", "guard_loss", "structured_event"]

_EXPORTS = {
    "MetricsLogger": ("dalle_pytorch_tpu.utils.metrics", "MetricsLogger"),
    "structured_event": ("dalle_pytorch_tpu.utils.metrics",
                         "structured_event"),
    "StepProfiler": ("dalle_pytorch_tpu.utils.profiling", "StepProfiler"),
    "trace": ("dalle_pytorch_tpu.utils.profiling", "trace"),
    "enable_nan_checks": ("dalle_pytorch_tpu.utils.debug",
                          "enable_nan_checks"),
    "check_finite_tree": ("dalle_pytorch_tpu.utils.debug",
                          "check_finite_tree"),
    "guard_loss": ("dalle_pytorch_tpu.utils.debug", "guard_loss"),
}


def __getattr__(name):
    if name in _EXPORTS:
        import importlib
        module, attr = _EXPORTS[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
