"""Interop with the reference PyTorch implementation: ``.pth`` checkpoint
import (reference trainVAE.py:119 / trainDALLE.py:212 save format) into
this package's pytrees, and export back out. Torch (CPU) is only imported
when used."""

from dalle_pytorch_tpu.compat.torch_export import (export_clip, export_dalle,
                                                   export_transformer,
                                                   export_vae,
                                                   save_torch_state_dict)
from dalle_pytorch_tpu.compat.torch_import import (import_clip, import_dalle,
                                                   import_transformer,
                                                   import_vae,
                                                   load_torch_state_dict)

__all__ = ["import_clip", "import_dalle", "import_transformer",
           "import_vae", "load_torch_state_dict",
           "export_clip", "export_dalle", "export_transformer",
           "export_vae", "save_torch_state_dict"]
