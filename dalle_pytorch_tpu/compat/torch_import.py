"""Import reference DALLE-pytorch ``.pth`` checkpoints into this framework.

The reference trains with ``torch.save(model.state_dict(), path)``
(reference trainVAE.py:119, trainDALLE.py:212); users switching from it
carry those files. This module maps the reference's parameter naming and
torch layouts onto this package's pytrees:

* torch ``nn.Linear`` weight ``(out, in)`` -> ``w (in, out)``;
* torch ``nn.Conv2d`` weight ``(O, I, kh, kw)`` -> HWIO ``(kh, kw, I, O)``
  (models run NHWC, SURVEY.md §7);
* torch ``nn.ConvTranspose2d`` weight ``(I, O, kh, kw)`` -> HWIO, spatial
  flip left to ``ops.core.conv2d_transpose`` (it flips internally);
* ``nn.LayerNorm`` weight/bias -> ``g``/``b``;
* per-layer transformer modules (reference transformer.py:137-169
  ``layers.layers.{i}.{0,1}``, or ``layers.blocks.{i}.{f,g}.net`` when saved
  with ``reversible=True``, reference reversible.py:143-157) -> the stacked
  depth-major arrays ``ops.transformer`` scans over;
* the axial image position embedding's ParameterList (summed-mode
  ``image_pos_emb.weights.{0,1}``, reference dalle_pytorch.py:268) ->
  ``rows``/``cols`` tables (use ``axial_compat='full_image'`` in
  ``DALLEConfig`` for imported checkpoints — the reference builds the
  table over (image_size, image_size), SURVEY.md §5).

Model structure (layer counts, dims) is INFERRED from the state dict so a
checkpoint can be loaded without re-specifying hyperparameters; the
returned config-kwargs dicts feed straight into VAEConfig/DALLEConfig/
CLIPConfig. Only ``image_size`` cannot be inferred for the VAE (convs are
size-agnostic) — pass it when it isn't the 256 default.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# loading + layout primitives
# ---------------------------------------------------------------------------

def load_torch_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a ``.pth`` state_dict into plain numpy (torch CPU only)."""
    import torch
    obj = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(obj, "state_dict"):     # a whole module was saved
        obj = obj.state_dict()
    return {k: v.detach().cpu().numpy() for k, v in obj.items()}


def _np(sd: Dict[str, np.ndarray], key: str) -> np.ndarray:
    if key not in sd:
        raise KeyError(f"state dict is missing {key!r} — not a reference-"
                       "layout checkpoint?")
    return np.asarray(sd[key], np.float32)


def _linear(sd, prefix: str, bias: bool = True) -> dict:
    p = {"w": _np(sd, prefix + ".weight").T}
    if bias:
        p["b"] = _np(sd, prefix + ".bias")
    return p


def _layernorm(sd, prefix: str) -> dict:
    return {"g": _np(sd, prefix + ".weight"), "b": _np(sd, prefix + ".bias")}


def _conv(sd, prefix: str) -> dict:
    return {"w": _np(sd, prefix + ".weight").transpose(2, 3, 1, 0),
            "b": _np(sd, prefix + ".bias")}


def _conv_transpose(sd, prefix: str) -> dict:
    return {"w": _np(sd, prefix + ".weight").transpose(2, 3, 0, 1),
            "b": _np(sd, prefix + ".bias")}


def _sub(sd: Dict[str, np.ndarray], prefix: str) -> Dict[str, np.ndarray]:
    pl = len(prefix)
    return {k[pl:]: v for k, v in sd.items() if k.startswith(prefix)}


def _index_count(sd, pattern: str) -> int:
    idx = {int(m.group(1)) for k in sd
           if (m := re.match(pattern, k)) is not None}
    return max(idx) + 1 if idx else 0


# ---------------------------------------------------------------------------
# transformer stack
# ---------------------------------------------------------------------------

def _resolve_layer_prefixes(sd, i: int) -> Tuple[str, str]:
    """(attn module prefix, ff module prefix) for layer i under either
    execution engine's naming."""
    seq = f"layers.layers.{i}."
    rev = f"layers.blocks.{i}."
    if any(k.startswith(seq) for k in sd):
        return seq + "0.", seq + "1."
    if any(k.startswith(rev) for k in sd):
        # reversible blocks wrap each branch in Deterministic(.net)
        # (reference reversible.py:20-27,56-58)
        return rev + "f.net.", rev + "g.net."
    raise KeyError(f"no transformer layer {i} found (checked {seq!r} and "
                   f"{rev!r})")


def import_transformer(sd: Dict[str, np.ndarray]) -> dict:
    """Transformer params stacked depth-major, from keys relative to the
    reference ``Transformer`` module (reference transformer.py:154-169)."""
    depth = max(_index_count(sd, r"layers\.layers\.(\d+)\."),
                _index_count(sd, r"layers\.blocks\.(\d+)\."))
    if depth == 0:
        raise KeyError("no transformer layers in state dict")
    layers = []
    for i in range(depth):
        attn_p, ff_p = _resolve_layer_prefixes(sd, i)
        layers.append({
            "attn": {
                "ln": _layernorm(sd, attn_p + "norm"),
                "qkv": _linear(sd, attn_p + "fn.to_qkv", bias=False),
                "out": _linear(sd, attn_p + "fn.to_out.0"),
            },
            "ff": {
                "ln": _layernorm(sd, ff_p + "norm"),
                "w1": _linear(sd, ff_p + "fn.net.0"),
                "w2": _linear(sd, ff_p + "fn.net.3"),
            },
        })
    import jax
    return jax.tree.map(lambda *xs: np.stack(xs), *layers)


def _transformer_dims(sd) -> Tuple[int, int, int]:
    """(depth, dim, inner_dim) from a transformer-relative state dict."""
    depth = max(_index_count(sd, r"layers\.layers\.(\d+)\."),
                _index_count(sd, r"layers\.blocks\.(\d+)\."))
    attn_p, _ = _resolve_layer_prefixes(sd, 0)
    qkv = _np(sd, attn_p + "fn.to_qkv.weight")      # (3*inner, dim)
    return depth, qkv.shape[1], qkv.shape[0] // 3


# ---------------------------------------------------------------------------
# DiscreteVAE
# ---------------------------------------------------------------------------

def _import_resblock(sd, prefix: str) -> dict:
    # ResBlock.net = Conv3x3, ReLU, Conv3x3, ReLU, Conv1x1
    # (reference dalle_pytorch.py:51-60)
    return {"c1": _conv(sd, prefix + "net.0"),
            "c2": _conv(sd, prefix + "net.2"),
            "c3": _conv(sd, prefix + "net.4")}


def import_vae(sd: Dict[str, np.ndarray],
               image_size: int = 256) -> Tuple[dict, dict]:
    """-> (params, config_kwargs). Encoder/decoder Sequential indices follow
    the reference construction (reference dalle_pytorch.py:88-119): encoder
    = L stride-2 convs, R resblocks, 1x1 head; decoder = [1x1 stem when R>0,]
    R resblocks, L transposed convs, 1x1 head."""
    L = _index_count(sd, r"encoder\.(\d+)\.0\.weight")
    R = sum(1 for k in sd if re.match(r"encoder\.\d+\.net\.0\.weight", k))

    codebook = _np(sd, "codebook.weight")
    params: dict = {"codebook": {"w": codebook}}
    params["enc_convs"] = [_conv(sd, f"encoder.{i}.0") for i in range(L)]
    params["enc_res"] = [_import_resblock(sd, f"encoder.{L + r}.")
                         for r in range(R)]
    params["enc_out"] = _conv(sd, f"encoder.{L + R}")

    off = 0
    if R > 0:
        params["dec_stem"] = _conv(sd, "decoder.0")
        off = 1
    params["dec_res"] = [_import_resblock(sd, f"decoder.{off + r}.")
                         for r in range(R)]
    params["dec_convs"] = [_conv_transpose(sd, f"decoder.{off + R + i}.0")
                           for i in range(L)]
    params["dec_out"] = _conv(sd, f"decoder.{off + R + L}")

    cfg = {
        "image_size": image_size,
        "num_tokens": codebook.shape[0],
        "codebook_dim": codebook.shape[1],
        "num_layers": L,
        "num_resnet_blocks": R,
        "hidden_dim": params["enc_convs"][0]["w"].shape[-1],
        "channels": params["enc_convs"][0]["w"].shape[-2],
    }
    return params, cfg


# ---------------------------------------------------------------------------
# DALLE
# ---------------------------------------------------------------------------

def _axial_tables(sd, prefix: str) -> dict:
    """Summed-mode AxialPositionalEmbedding ParameterList -> rows/cols.
    weights.{i} carries axial_shape[i] on axis i+1 with singleton other
    axes (reference dalle_pytorch.py:268 uses axial_shape=(image_size,
    image_size))."""
    tables = []
    i = 0
    while f"{prefix}weights.{i}" in sd:
        w = _np(sd, f"{prefix}weights.{i}")
        tables.append(w.reshape(-1, w.shape[-1]))   # squeeze singletons
        i += 1
    if len(tables) != 2:
        raise KeyError(
            f"expected 2 axial tables under {prefix}weights.*, got "
            f"{len(tables)} (concat-mode axial embeddings are not used by "
            "the reference)")
    return {"rows": tables[0], "cols": tables[1]}


def _dim_head_for(inner: int, heads: int) -> int:
    if inner % heads:
        raise ValueError(
            f"heads={heads} does not divide the checkpoint's attention "
            f"inner dim {inner}; pass the head count the checkpoint was "
            "trained with")
    return inner // heads


def import_dalle(sd: Dict[str, np.ndarray], image_size: int = 256,
                 heads: int = 8):
    """-> (dalle_params, vae_params, dalle_cfg_kwargs, vae_cfg_kwargs).

    The reference DALLE state dict embeds the full VAE (``vae.*``) and ties
    ``image_emb.weight`` to ``vae.codebook.weight`` (reference
    dalle_pytorch.py:283); both copies land in their owners here — DALLE
    owns the live table (models.dalle docstring), the VAE convs keep theirs
    for decoding. Use ``axial_compat='full_image'`` in the DALLEConfig built
    from the returned kwargs.

    ``heads`` cannot be inferred from a fused qkv weight; pass the value the
    checkpoint was trained with (reference default 8) — a wrong split changes
    attention numerics silently, so non-divisible values are rejected."""
    vae_sd = _sub(sd, "vae.")
    vae_params, vae_cfg = (import_vae(vae_sd, image_size) if vae_sd
                           else (None, None))

    tsd = _sub(sd, "transformer.")
    depth, dim, inner = _transformer_dims(tsd)
    text_emb = _np(sd, "text_emb.weight")
    params = {
        "text_emb": {"w": text_emb},
        "image_emb": {"w": _np(sd, "image_emb.weight")},
        "text_pos_emb": {"w": _np(sd, "text_pos_emb.weight")},
        "image_pos_emb": _axial_tables(sd, "image_pos_emb."),
        "transformer": import_transformer(tsd),
        "to_logits": {
            "ln": _layernorm(sd, "to_logits.0"),
            "proj": _linear(sd, "to_logits.1"),
        },
    }
    cfg = {
        "dim": dim,
        "depth": depth,
        "num_text_tokens": text_emb.shape[0],
        "text_seq_len": _np(sd, "text_pos_emb.weight").shape[0],
        "dim_head": _dim_head_for(inner, heads),
        "axial_compat": "full_image",
    }
    return params, vae_params, cfg, vae_cfg


# ---------------------------------------------------------------------------
# CLIP
# ---------------------------------------------------------------------------

def import_clip(sd: Dict[str, np.ndarray]) -> Tuple[dict, dict]:
    """-> (params, config_kwargs) for the reference CLIP
    (reference dalle_pytorch.py:161-195)."""
    text_t = _sub(sd, "text_transformer.")
    vis_t = _sub(sd, "visual_transformer.")
    t_depth, dim_text, _ = _transformer_dims(text_t)
    v_depth, dim_image, _ = _transformer_dims(vis_t)
    to_vis = _np(sd, "to_visual_embedding.weight")   # (dim_image, patch_dim)
    vis_pos = _np(sd, "visual_pos_emb.weight")
    text_emb = _np(sd, "text_emb.weight")

    params = {
        "text_emb": {"w": text_emb},
        "text_pos_emb": {"w": _np(sd, "text_pos_emb.weight")},
        "text_transformer": import_transformer(text_t),
        "to_text_latent": _linear(sd, "to_text_latent", bias=False),
        "to_visual_emb": _linear(sd, "to_visual_embedding"),
        "visual_pos_emb": {"w": vis_pos},
        "visual_transformer": import_transformer(vis_t),
        "to_visual_latent": _linear(sd, "to_visual_latent", bias=False),
        "temperature": np.asarray(_np(sd, "temperature"), np.float32)
                         .reshape(()),
    }
    patch_dim = to_vis.shape[1]
    num_patches = vis_pos.shape[0]
    # patch_dim = channels * p**2; channels=3 unless indivisible (gray=1)
    channels = 3 if patch_dim % 3 == 0 else 1
    patch = int(round((patch_dim // channels) ** 0.5))
    side = int(round(num_patches ** 0.5))
    cfg = {
        "dim_text": dim_text,
        "dim_image": dim_image,
        "dim_latent": _np(sd, "to_text_latent.weight").shape[0],
        "num_text_tokens": text_emb.shape[0],
        "text_enc_depth": t_depth,
        "text_seq_len": _np(sd, "text_pos_emb.weight").shape[0],
        "visual_enc_depth": v_depth,
        "visual_image_size": side * patch,
        "visual_patch_size": patch,
        "channels": channels,
    }
    return params, cfg
