"""Export this framework's pytrees to reference-layout torch state dicts —
the inverse of ``compat.torch_import``, so models trained here drop back
into the reference PyTorch ecosystem (same key names and tensor layouts the
reference's ``load_state_dict`` resume path reads, reference
trainVAE.py:52-54, trainDALLE.py:64-67).

Layout transforms mirror torch conventions exactly (see torch_import's
module docstring); ``import_*(export_*(params))`` round-trips bit-exactly,
which the tests pin.
"""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np


def _t(x) -> np.ndarray:
    return np.asarray(x, np.float32)


def _linear(out: Dict[str, np.ndarray], prefix: str, p: dict) -> None:
    if "w_q" in p:
        # covers the quantize_for_decode surface (transformer linears +
        # vocab head all pass through here); embedding/conv reads on a
        # broader hand-quantized tree still KeyError — don't do that
        raise ValueError(
            f"{prefix}: int8-quantized weights (ops.quant) cannot be "
            "exported — quantization is lossy and inference-only; export "
            "the checkpointed full-precision params instead")
    out[prefix + ".weight"] = _t(p["w"]).T
    if "b" in p:
        out[prefix + ".bias"] = _t(p["b"])


def _layernorm(out, prefix: str, p: dict) -> None:
    out[prefix + ".weight"] = _t(p["g"])
    out[prefix + ".bias"] = _t(p["b"])


def _conv(out, prefix: str, p: dict) -> None:
    out[prefix + ".weight"] = _t(p["w"]).transpose(3, 2, 0, 1)   # HWIO->OIHW
    out[prefix + ".bias"] = _t(p["b"])


def _conv_transpose(out, prefix: str, p: dict) -> None:
    out[prefix + ".weight"] = _t(p["w"]).transpose(2, 3, 0, 1)   # HWIO->IOHW
    out[prefix + ".bias"] = _t(p["b"])


def _resblock(out, prefix: str, p: dict) -> None:
    _conv(out, prefix + "net.0", p["c1"])
    _conv(out, prefix + "net.2", p["c2"])
    _conv(out, prefix + "net.4", p["c3"])


def export_vae(params: dict) -> Dict[str, np.ndarray]:
    """VAE pytree -> reference DiscreteVAE state dict (Sequential indices
    per reference dalle_pytorch.py:88-119)."""
    out: Dict[str, np.ndarray] = {"codebook.weight": _t(
        params["codebook"]["w"])}
    L = len(params["enc_convs"])
    R = len(params["enc_res"])
    for i, p in enumerate(params["enc_convs"]):
        _conv(out, f"encoder.{i}.0", p)
    for r, p in enumerate(params["enc_res"]):
        _resblock(out, f"encoder.{L + r}.", p)
    _conv(out, f"encoder.{L + R}", params["enc_out"])

    off = 1 if "dec_stem" in params else 0
    if off:
        _conv(out, "decoder.0", params["dec_stem"])
    for r, p in enumerate(params["dec_res"]):
        _resblock(out, f"decoder.{off + r}.", p)
    for i, p in enumerate(params["dec_convs"]):
        _conv_transpose(out, f"decoder.{off + R + i}.0", p)
    _conv(out, f"decoder.{off + R + L}", params["dec_out"])
    return out


def export_transformer(stacked: dict) -> Dict[str, np.ndarray]:
    """Depth-stacked transformer params -> per-layer reference keys
    (``layers.layers.{i}.{0,1}...``, the SequentialSequence naming)."""
    out: Dict[str, np.ndarray] = {}
    if "moe" in stacked.get("ff", {}):
        raise ValueError(
            "MoE layers cannot be exported to the reference .pth format "
            "(the reference has no MoE; its FeedForward is a single GEGLU "
            "MLP) — train with moe_experts=0 for torch-compatible export")
    depth = jax.tree.leaves(stacked)[0].shape[0]
    for i in range(depth):
        lp = jax.tree.map(lambda a: a[i], stacked)
        a = f"layers.layers.{i}.0."
        _layernorm(out, a + "norm", lp["attn"]["ln"])
        _linear(out, a + "fn.to_qkv", lp["attn"]["qkv"])
        _linear(out, a + "fn.to_out.0", lp["attn"]["out"])
        f = f"layers.layers.{i}.1."
        _layernorm(out, f + "norm", lp["ff"]["ln"])
        _linear(out, f + "fn.net.0", lp["ff"]["w1"])
        _linear(out, f + "fn.net.3", lp["ff"]["w2"])
    return out


def export_dalle(params: dict, vae_params: dict = None,
                 image_size: int = 256) -> Dict[str, np.ndarray]:
    """DALLE pytree -> reference state dict. ``vae_params`` fills the
    embedded ``vae.*`` subtree; the tied ``image_emb``/codebook uses
    DALLE's live table (it owns the trained copy, models.dalle docstring,
    reference dalle_pytorch.py:283)."""
    out: Dict[str, np.ndarray] = {}
    out["text_emb.weight"] = _t(params["text_emb"]["w"])
    out["image_emb.weight"] = _t(params["image_emb"]["w"])
    out["text_pos_emb.weight"] = _t(params["text_pos_emb"]["w"])
    rows = _t(params["image_pos_emb"]["rows"])
    cols = _t(params["image_pos_emb"]["cols"])
    dim = rows.shape[-1]
    out["image_pos_emb.weights.0"] = rows.reshape(1, rows.shape[0], 1, dim)
    out["image_pos_emb.weights.1"] = cols.reshape(1, 1, cols.shape[0], dim)
    for k, v in export_transformer(params["transformer"]).items():
        out[f"transformer.{k}"] = v
    _layernorm(out, "to_logits.0", params["to_logits"]["ln"])
    _linear(out, "to_logits.1", params["to_logits"]["proj"])
    if vae_params is not None:
        vae_sd = export_vae(vae_params)
        # the reference's tie makes vae.codebook the same tensor as
        # image_emb; keep the export consistent with DALLE's trained copy
        vae_sd["codebook.weight"] = out["image_emb.weight"]
        for k, v in vae_sd.items():
            out[f"vae.{k}"] = v
    return out


def export_clip(params: dict) -> Dict[str, np.ndarray]:
    """CLIP pytree -> reference state dict (dalle_pytorch.py:180-195)."""
    out: Dict[str, np.ndarray] = {}
    out["text_emb.weight"] = _t(params["text_emb"]["w"])
    out["text_pos_emb.weight"] = _t(params["text_pos_emb"]["w"])
    for k, v in export_transformer(params["text_transformer"]).items():
        out[f"text_transformer.{k}"] = v
    _linear(out, "to_text_latent", params["to_text_latent"])
    _linear(out, "to_visual_embedding", params["to_visual_emb"])
    out["visual_pos_emb.weight"] = _t(params["visual_pos_emb"]["w"])
    for k, v in export_transformer(params["visual_transformer"]).items():
        out[f"visual_transformer.{k}"] = v
    _linear(out, "to_visual_latent", params["to_visual_latent"])
    out["temperature"] = _t(params["temperature"]).reshape(())
    return out


def save_torch_state_dict(sd: Dict[str, np.ndarray], path: str) -> None:
    """Write as a torch-loadable ``.pth`` (torch CPU)."""
    import torch
    torch.save({k: torch.tensor(v) for k, v in sd.items()}, path)
