"""L6 driver CLIs: train_vae, train_dalle, gen_dalle, mix_vae.

TPU-native rebuilds of the reference scripts (trainVAE.py, trainDALLE.py,
genDALLE.py, mixVAEcuda.py): same flag surface and artifacts, but jit train
steps over a device mesh, prefetched host IO, KV-cache sampling, and
checkpoints with optimizer state. Run as modules, e.g.
``python -m dalle_pytorch_tpu.cli.train_vae --help``.
"""
