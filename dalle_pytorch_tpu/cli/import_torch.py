"""Convert a reference DALLE-pytorch ``.pth`` into this framework's
checkpoint format.

The reference's cross-program contract is weight files written by its
training scripts and read everywhere else (reference trainVAE.py:119,
trainDALLE.py:64-67, genDALLE.py:51-52, mixVAEcuda.py:20-21). This CLI
closes the migration path: a user's existing ``.pth`` becomes a checkpoint
directory that train_vae/train_dalle/gen_dalle/mix_vae resume from
directly.

    python -m dalle_pytorch_tpu.cli.import_torch vae mytrained.pth \
        --out ./models/vae-99 [--image_size 256]

    python -m dalle_pytorch_tpu.cli.import_torch dalle dalle.pth \
        --out ./models/dalle-0 [--heads 8] [--vae_out ./models/vae-0]

For DALLE the embedded VAE (reference ties it into the DALLE state dict,
dalle_pytorch.py:283) can be written as its own checkpoint too, so the
whole pipeline is reconstructed from one file.

The export-* kinds run the other direction — a framework checkpoint
becomes a reference-layout ``.pth`` torch's ``load_state_dict`` accepts:

    python -m dalle_pytorch_tpu.cli.import_torch export-vae out.pth \
        --out ./models/vae-99
"""

from __future__ import annotations

import argparse

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.compat import (import_clip, import_dalle, import_vae,
                                      load_torch_state_dict)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="import a reference DALLE-pytorch .pth checkpoint")
    p.add_argument("kind", choices=["vae", "dalle", "clip",
                                    "export-vae", "export-dalle",
                                    "export-clip"])
    p.add_argument("pth", help="torch state_dict path (the OUTPUT for "
                               "export-* kinds)")
    p.add_argument("--out", required=True,
                   help="checkpoint directory (output for imports, INPUT "
                        "for export-* kinds)")
    p.add_argument("--image_size", type=int, default=256,
                   help="VAE training image size (not stored in weights)")
    p.add_argument("--heads", type=int, default=8,
                   help="attention heads (not inferable from weights)")
    p.add_argument("--epoch", type=int, default=0,
                   help="epoch number recorded in the checkpoint")
    p.add_argument("--vae_out", default="",
                   help="dalle only: also write the embedded VAE here")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    if args.kind.startswith("export-"):
        # checkpoint dir -> reference-layout .pth (compat.torch_export)
        from dalle_pytorch_tpu.compat import (export_clip, export_dalle,
                                              export_vae,
                                              save_torch_state_dict)
        params, manifest = ckpt.restore_params(args.out)
        kind = args.kind.removeprefix("export-")
        if manifest.get("kind") not in (kind, "model"):
            raise SystemExit(f"checkpoint {args.out} is kind="
                             f"{manifest.get('kind')!r}, expected {kind!r}")
        if kind == "vae":
            sd = export_vae(params)
        elif kind == "clip":
            sd = export_clip(params)
        else:
            vae_path = manifest.get("meta", {}).get("vae_checkpoint")
            vae_params = None
            if vae_path:
                vae_params, _ = ckpt.restore_params(vae_path)
            sd = export_dalle(params, vae_params)
        save_torch_state_dict(sd, args.pth)
        print(f"wrote reference-layout state dict {args.pth} "
              f"({len(sd)} tensors)")
        return

    sd = load_torch_state_dict(args.pth)

    if args.kind == "vae":
        from dalle_pytorch_tpu.models.vae import VAEConfig
        params, cfg_kw = import_vae(sd, image_size=args.image_size)
        path = ckpt.save(args.out, params, step=args.epoch,
                         config=VAEConfig(**cfg_kw), kind="vae",
                         meta={"imported_from": args.pth,
                               "epoch": args.epoch})
        print(f"wrote VAE checkpoint {path} "
              f"({cfg_kw['num_tokens']} tokens, {cfg_kw['num_layers']} "
              "layers)")
        return

    if args.kind == "dalle":
        from dalle_pytorch_tpu.models.dalle import DALLEConfig
        from dalle_pytorch_tpu.models.vae import VAEConfig
        try:
            params, vae_params, cfg_kw, vae_cfg_kw = import_dalle(
                sd, image_size=args.image_size, heads=args.heads)
        except ValueError as e:           # --heads doesn't divide inner dim
            raise SystemExit(str(e))
        if vae_params is None:
            raise SystemExit("this .pth has no embedded vae.* weights; "
                             "import the VAE separately")
        cfg = DALLEConfig(vae=VAEConfig(**vae_cfg_kw), heads=args.heads,
                          **cfg_kw)
        path = ckpt.save(args.out, params, step=args.epoch, config=cfg,
                         kind="dalle", meta={"imported_from": args.pth,
                                             "epoch": args.epoch})
        print(f"wrote DALLE checkpoint {path} (dim {cfg.dim}, depth "
              f"{cfg.depth})")
        if args.vae_out:
            vpath = ckpt.save(args.vae_out, vae_params, step=args.epoch,
                              config=VAEConfig(**vae_cfg_kw), kind="vae",
                              meta={"imported_from": args.pth,
                                    "epoch": args.epoch})
            print(f"wrote embedded VAE checkpoint {vpath}")
        return

    from dalle_pytorch_tpu.models.clip import CLIPConfig
    params, cfg_kw = import_clip(sd)
    cfg = CLIPConfig(text_heads=args.heads, visual_heads=args.heads,
                     **cfg_kw)
    path = ckpt.save(args.out, params, step=args.epoch, config=cfg,
                     kind="clip", meta={"imported_from": args.pth,
                                        "epoch": args.epoch})
    print(f"wrote CLIP checkpoint {path}")


if __name__ == "__main__":
    main()
