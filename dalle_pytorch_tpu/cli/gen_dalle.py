"""Text -> image generation CLI — the reference genDALLE.py, TPU-native.

Capability parity (reference genDALLE.py:1-113): rebuilds the training
vocabulary (from the saved vocab JSON train_dalle writes, or by re-reading
the captions-only corpus exactly as the reference does, :77-93), tokenizes
the caption, and — deliberately preserving the reference's quirk — passes
the UNPADDED token list (reference :106 uses ``codes``, not the padded
``c_tokens``), so the model first autoregressively completes the remaining
text positions, then the image tokens. OOV words KeyError, the reference's
documented failure mode (Vocabulary.py:43, SURVEY.md §5.3). Output is a
timestamped PNG grid (reference :109-112).

TPU-first: generation is the jit ``lax.scan`` KV-cache sampler — one
compiled program for all 1024+ steps instead of full re-forwards; optional
CLIP rerank scores the batch and orders the grid best-first (reference
dalle_pytorch.py:354-356).

Run: python -m dalle_pytorch_tpu.cli.gen_dalle "a caption" --name test \
        --dalle_epoch 99 --vaename vae --vae_epoch 99
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.cli.common import ema_as, say
from dalle_pytorch_tpu.data import (Vocabulary, read_captions_only,
                                    save_image_grid)
from dalle_pytorch_tpu.models import dalle as D


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="generate images from text (TPU-native DALLE-pytorch)")
    p.add_argument("caption", type=str, help="input text")
    p.add_argument("--name", type=str, default="test",
                   help="DALLE experiment name (as given to train_dalle)")
    p.add_argument("--dalle_epoch", type=int, default=0)
    p.add_argument("--models_dir", type=str, default="./models")
    p.add_argument("--results_dir", type=str, default="./results")
    p.add_argument("--vocab", type=str, default="",
                   help="vocab JSON (default: {models_dir}/{name}-vocab.json)")
    p.add_argument("--captions_only", type=str, default="",
                   help="rebuild vocab from this corpus instead")
    p.add_argument("--num_images", type=int, default=1,
                   help="images to sample for the caption")
    p.add_argument("--filter_thres", type=float, default=0.5)
    def _top_p(v):
        v = float(v)
        if not 0.0 <= v <= 1.0:
            raise argparse.ArgumentTypeError(
                f"--top_p must be in [0, 1], got {v}")
        return v

    p.add_argument("--top_p", type=_top_p, default=0.0,
                   help="nucleus sampling: keep the top tokens holding "
                        "this much probability mass, in (0, 1] "
                        "(0 = the reference's top-k filter via "
                        "--filter_thres)")
    p.add_argument("--temperature", type=float, default=1.0)
    def _guidance(v):
        v = float(v)
        if v < 0:
            raise argparse.ArgumentTypeError(
                f"--guidance must be >= 0, got {v}")
        return v

    p.add_argument("--guidance", type=_guidance, default=0.0,
                   help="classifier-free guidance scale (e.g. 3.0; 0 = "
                        "off, 1.0 = plain conditional): image tokens "
                        "sample from uncond + s*(cond - uncond), with the "
                        "all-PAD null caption as the unconditional "
                        "stream. Train with --caption_drop first")
    p.add_argument("--pad_prompt", action="store_true",
                   help="pad the prompt to text_seq_len instead of the "
                        "reference's unpadded text-completion mode")
    p.add_argument("--clip_name", type=str, default="",
                   help="CLIP checkpoint name for reranking")
    p.add_argument("--clip_epoch", type=int, default=0)
    p.add_argument("--scores_json", type=str, default="",
                   help="append a JSONL record {caption, guidance, "
                        "scores, mean_score} per run (requires "
                        "--clip_name) — machine-readable prompt-"
                        "adherence evidence for guidance sweeps")
    p.add_argument("--use_ema", action="store_true",
                   help="sample from the checkpoint's EMA weights "
                        "(train_dalle --ema_decay); errors if the DALLE "
                        "checkpoint has none. A CLIP rerank checkpoint "
                        "without EMA falls back to raw weights with a "
                        "note")
    p.add_argument("--quantize", choices=("none", "int8", "int8_kv"),
                   default="none",
                   help="int8: quantize the transformer linears + vocab "
                        "head after restore (halves per-token weight HBM "
                        "traffic; ops/quant.py); int8_kv: additionally "
                        "store the KV cache int8 with per-row scales "
                        "(halves the cache read share too — the dominant "
                        "decode bytes at num_images > 1)")
    p.add_argument("--seed", type=int, default=0)
    return p


def load_vocab(args) -> Vocabulary:
    if args.captions_only:
        return Vocabulary.from_captions(read_captions_only(
            args.captions_only))
    path = args.vocab or os.path.join(args.models_dir,
                                      f"{args.name}-vocab.json")
    return Vocabulary.load(path)


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.scores_json and not args.clip_name:
        # fail at the flag, not in a downstream aggregator reading a file
        # that was silently never written
        parser.error("--scores_json needs --clip_name (the scores come "
                     "from the CLIP rerank)")

    dalle_path = ckpt.ckpt_path(args.models_dir, f"{args.name}_dalle",
                                args.dalle_epoch)
    params, manifest = ckpt.restore_params(dalle_path)
    cfg = ckpt.dalle_config_from_manifest(manifest)
    vae_path = manifest["meta"].get("vae_checkpoint")
    if not vae_path or not os.path.isdir(vae_path):
        raise FileNotFoundError(
            f"DALLE checkpoint {dalle_path} does not point at a VAE "
            "checkpoint (meta.vae_checkpoint)")
    vae_params, _ = ckpt.restore_params(vae_path)
    if args.use_ema:
        ema = ckpt.restore_ema(dalle_path)
        if ema is None:
            raise FileNotFoundError(
                f"{dalle_path} has no EMA weights — train with "
                "--ema_decay to sample from an EMA")
        params = ema_as(ema, params)
        say("sampling from EMA weights")
    # restored trees are host numpy; the scan sampler indexes tables with
    # traced positions, which needs device arrays
    params = jax.device_put(params)
    vae_params = jax.device_put(vae_params)
    if args.quantize in ("int8", "int8_kv"):
        params = D.quantize_for_decode(params)

    vocab = load_vocab(args)
    say(args.caption)
    codes = vocab.encode(args.caption,
                         pad_to=cfg.text_seq_len if args.pad_prompt
                         else None)
    say(codes)

    text = jnp.asarray([codes] * args.num_images, jnp.int32)

    clip_kwargs = {}
    if args.clip_name:
        clip_path = ckpt.ckpt_path(args.models_dir, args.clip_name,
                                   args.clip_epoch)
        clip_params, clip_manifest = ckpt.restore_params(clip_path)
        if args.use_ema:
            clip_ema = ckpt.restore_ema(clip_path)
            if clip_ema is not None:
                clip_params = ema_as(clip_ema, clip_params)
                say("reranking with CLIP EMA weights")
            else:
                say("note: CLIP checkpoint has no EMA weights; "
                    "reranking with raw weights")
        from dalle_pytorch_tpu.models.clip import CLIPConfig
        clip_kwargs = {"clip_params": clip_params,
                       "clip_cfg": CLIPConfig(**clip_manifest["config"])}

    # ONE jit program (prefill + KV-cache decode scan + VAE decode [+ CLIP
    # rerank]) — not per-op dispatch. clip_cfg is static (closed over);
    # clip params are a traced pytree argument.
    clip_cfg = clip_kwargs.pop("clip_cfg", None)

    @jax.jit
    def gen(p, vp, t, rng, clip_p):
        kw = {} if clip_p is None else {"clip_params": clip_p,
                                        "clip_cfg": clip_cfg}
        return D.generate_images(p, vp, t, cfg=cfg, rng=rng,
                                 filter_thres=args.filter_thres,
                                 top_p=args.top_p, guidance=args.guidance,
                                 temperature=args.temperature,
                                 quantize_cache=args.quantize == "int8_kv",
                                 **kw)

    out = gen(params, vae_params, text, jax.random.PRNGKey(args.seed),
              clip_kwargs.get("clip_params"))

    if clip_kwargs:
        images, scores = out
        order = np.argsort(-np.asarray(scores))    # best first
        images = np.asarray(images)[order]
        say("clip scores (sorted):", np.asarray(scores)[order])
        if args.scores_json:
            # machine-readable adherence record (JSONL, appended): the
            # demo's guidance sweep aggregates mean CLIP score per scale
            # — quantitative CFG evidence, not just eyeballed grids
            import json
            rec = {"caption": args.caption, "guidance": args.guidance,
                   "scores": [float(s) for s in np.asarray(scores)[order]],
                   "mean_score": float(np.mean(np.asarray(scores)))}
            os.makedirs(os.path.dirname(
                os.path.abspath(args.scores_json)), exist_ok=True)
            with open(args.scores_json, "a") as f:
                f.write(json.dumps(rec) + "\n")
            say(f"appended scores to {args.scores_json}")
    else:
        images = np.asarray(out)

    ts = int(time.time())  # jaxlint: disable=JL007 — filename epoch stamp
    say(args.caption, ts)
    path = os.path.join(
        args.results_dir,
        f"gendalle{args.name}_epoch_{args.dalle_epoch}-{ts}.png")
    save_image_grid(images, path, nrow=min(args.num_images, 8))
    say(f"saved {path}")


if __name__ == "__main__":
    main()
