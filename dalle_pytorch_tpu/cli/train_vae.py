"""DiscreteVAE training CLI — the reference trainVAE.py, TPU-native.

Capability parity (reference trainVAE.py:1-119): argparse flags with the
same names, Adam, loss = smooth_l1 + mse (reference :87), optional per-epoch
temperature decay ``0.7 ** (1/len(loader))`` (reference :78,104-105),
optional per-step weight clamping (reference :71-74,95-96), per-epoch
[input | recon | decode(argmax codes)] grids (reference :109-114), and a
per-epoch checkpoint under ``{models_dir}/{name}-{epoch}`` (reference :119,
the cross-CLI contract train_dalle/gen_dalle/mix_vae read).

TPU-first differences:
  * ONE jit-compiled train step (loss+grads+adam+clamp fused by XLA) over a
    ``dp`` mesh — batch sharded, gradient psum over ICI; the temperature is
    a traced scalar input so the schedule never recompiles;
  * host image loading is prefetched on a background thread while the chip
    runs the current step (data.prefetch);
  * checkpoints carry optimizer state + config, so resume is exact
    (improvement over the reference's weights-only .pth).

Run: python -m dalle_pytorch_tpu.cli.train_vae --dataPath ./imagedata
"""

from __future__ import annotations

import argparse
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.cli.common import (LoopState, add_common_args,
                                          make_optimizer, make_supervisor,
                                          plan_resume, resolve_schedule,
                                          restore_rollback,
                                          run_supervised_loop, say,
                                          setup_run, step_rng)
from dalle_pytorch_tpu.data import ImageFolderDataset, save_image_grid, \
    shard_for_host
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.parallel import shard_batch
from dalle_pytorch_tpu.parallel.train import setup_sharded


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="train DiscreteVAE (TPU-native DALLE-pytorch)")
    add_common_args(p, default_batch=24)
    p.add_argument("--dataPath", type=str, default="./imagedata",
                   help="path to image folder (default: ./imagedata)")
    p.add_argument("--imageSize", type=int, default=256)
    p.add_argument("--tempsched", action="store_true", default=False,
                   help="use temperature scheduling")
    p.add_argument("--temperature", type=float, default=0.9)
    p.add_argument("--loadVAE", type=str, default="",
                   help="checkpoint path (or name with --start_epoch) to "
                        "continue training")
    p.add_argument("--clip", type=float, default=0,
                   help="clamp weights to [-clip, clip], 0 = off")
    # model hyperparams (reference trainVAE.py:42-50 hardcodes these)
    p.add_argument("--num_layers", type=int, default=3)
    p.add_argument("--num_tokens", type=int, default=2048)
    p.add_argument("--codebook_dim", type=int, default=256)
    p.add_argument("--hidden_dim", type=int, default=128)
    p.add_argument("--num_resnet_blocks", type=int, default=0)
    p.add_argument("--straight_through", action="store_true")
    p.add_argument("--grad_accum", type=int, default=1,
                   help="accumulate gradients over this many microbatches "
                        "per optimizer step (batchSize must divide)")
    p.add_argument("--param_dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="dtype for NEW runs' params (bfloat16 halves HBM "
                        "and keeps every matmul on the MXU's native "
                        "precision; resumed runs keep the checkpoint's "
                        "dtype)")
    p.set_defaults(name="vae")
    return p


def make_step(cfg: V.VAEConfig, optimizer, clip: float,
              grad_accum: int = 1):
    """jit step: (params, opt_state, batch{'images','temperature'}, rng) ->
    (params, opt_state, loss). Loss = smooth_l1 + mse (reference
    trainVAE.py:87); the optional weight clamp runs inside the same compiled
    step (reference clampWeights applies per step, :71-74,95-96)."""

    def loss_fn(params, batch, rng):
        imgs = batch["images"]
        recon = V.vae_apply(params, imgs, cfg=cfg, rng=rng,
                            temperature=batch["temperature"])
        d = jnp.abs(imgs - recon)
        huber = jnp.mean(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5))
        return huber + jnp.mean(jnp.square(imgs - recon))

    from dalle_pytorch_tpu.parallel._compat import donate_if_accelerator
    donate = donate_if_accelerator(0, 1)

    @functools.partial(jax.jit, donate_argnums=donate)
    def step(params, opt_state, batch, rng):
        batch = dict(batch)
        # optional traced update scale (resilience LR re-warm) — for Adam
        # exactly an LR multiplier, like parallel.train.make_train_step
        lr_scale = batch.pop("lr_scale", None)
        if grad_accum > 1:
            from dalle_pytorch_tpu.parallel.train import accumulate_grads
            loss, grads = accumulate_grads(loss_fn, params, batch, rng,
                                           grad_accum)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if lr_scale is not None:
            updates = jax.tree.map(
                lambda u: (u * lr_scale).astype(u.dtype), updates)
        params = optax.apply_updates(params, updates)
        if clip > 0:
            params = jax.tree.map(lambda p: jnp.clip(p, -clip, clip), params)
        return params, opt_state, loss

    return step


def main(argv=None):
    args = build_parser().parse_args(argv)
    mesh, metrics, profiler = setup_run(args, unit_name="images")

    cfg = V.VAEConfig(
        image_size=args.imageSize, num_tokens=args.num_tokens,
        codebook_dim=args.codebook_dim, num_layers=args.num_layers,
        num_resnet_blocks=args.num_resnet_blocks,
        hidden_dim=args.hidden_dim, temperature=args.temperature,
        straight_through=args.straight_through)

    dataset = ImageFolderDataset(args.dataPath, args.imageSize,
                                 args.batchSize, shuffle=True,
                                 seed=args.seed)
    # multi-host: each process reads its slice of the files
    dataset.files = list(shard_for_host(dataset.files))

    key = jax.random.PRNGKey(args.seed)

    temperature = args.temperature
    # resolve the resume point BEFORE building the optimizer: the cosine
    # horizon must cover already-completed epochs too. --auto_resume picks
    # the newest VALID checkpoint (mid-epoch step checkpoints included),
    # whose persisted schedule snapshot reconstructs the original horizon.
    plan = plan_resume(args, args.name, explicit=args.loadVAE,
                       steps_per_epoch=len(dataset))
    start_epoch = plan["start_epoch"] if plan else args.start_epoch
    resume_path = plan["path"] if plan else None
    sched = resolve_schedule(args, steps_per_epoch=len(dataset),
                             start_epoch=start_epoch,
                             resume_meta=plan["meta"] if plan else None)
    optimizer = make_optimizer(args, schedule=sched)
    opt_state = None
    if resume_path:
        params, opt_state, manifest = ckpt.restore_train(resume_path,
                                                         optimizer)
        cfg = ckpt.vae_config_from_manifest(manifest)
        temperature = manifest["meta"].get("temperature", temperature)
        say(f"resumed VAE from {resume_path}")
        if plan["mid_epoch"]:
            metrics.resilience("resume", checkpoint=resume_path,
                               epoch=start_epoch,
                               step_in_epoch=plan["step_in_epoch"],
                               records_in_epoch=plan["skip_batches"],
                               global_step=plan["global_step"])
    else:
        params = V.vae_init(key, cfg, dtype=jnp.dtype(args.param_dtype))

    params, opt_state = setup_sharded(params, optimizer, mesh,
                                      opt_state=opt_state)
    step = make_step(cfg, optimizer, args.clip,
                     grad_accum=args.grad_accum)
    from dalle_pytorch_tpu.cli.common import make_ema
    ema, ema_update = make_ema(args, params, resume_path or "")

    dk = 0.7 ** (1.0 / max(len(dataset), 1))
    if args.tempsched:
        say("Scale Factor:", dk)

    @jax.jit
    def eval_fn(params, images, rng, temperature):
        """[gumbel recon | argmax-token decode] for the per-epoch grid
        (reference trainVAE.py:109-114)."""
        recon = V.vae_apply(params, images, cfg=cfg, rng=rng,
                            temperature=temperature)
        decoded = V.decode(params, V.get_codebook_indices(params, images))
        return recon, decoded

    # mutable loop state the supervisor's save_state closure reads live
    # (run_supervised_loop advances it)
    state = LoopState(epoch=start_epoch,
                      global_step=plan["global_step"] if plan else 0)

    def save_state(path):
        """Full mid-epoch train state — resume needs params, opt state,
        EMA, schedule meta AND the loop position (global_step/epoch/
        step_in_epoch + accumulators for the epoch summary)."""
        return ckpt.save(
            path, params, step=state.global_step, config=cfg,
            opt_state=opt_state, kind="vae",
            meta={"temperature": temperature, "epoch": state.epoch,
                  "step_in_epoch": state.epoch_i,
                  "global_step": state.global_step,
                  "records_in_epoch": state.records_in_epoch,
                  "train_loss": state.train_loss,
                  "n_batches": state.n_batches, "lr_schedule": sched,
                  **({"ema_decay": args.ema_decay} if ema is not None
                     else {})}, ema=ema)

    sup = make_supervisor(args, metrics, args.name, save_state)
    if resume_path:
        # the checkpoint we just restored from is a valid rollback
        # anchor — without it a NaN before the first cadence/epoch
        # save after resume would raise instead of rolling back
        sup.register_checkpoint(resume_path)

    def train_step(images, state):
        nonlocal params, opt_state, ema
        # every host->device crossing is explicit (shard_batch's
        # device_put, the device_put'd temperature scalar, step_rng) so
        # the body runs clean under --guard_transfers
        batch = shard_batch(mesh, {"images": images})
        batch["temperature"] = jax.device_put(np.float32(temperature))
        batch = sup.pre_step(state.global_step, batch)
        params, opt_state, loss = step(
            params, opt_state, batch,
            step_rng(key, state.global_step))
        if ema is not None:
            ema = ema_update(ema, params)
        return loss, batch

    def on_rollback(state):
        nonlocal params, opt_state, ema
        params, opt_state, ema = restore_rollback(sup, optimizer, mesh)

    def on_epoch_end(state, avg):
        nonlocal temperature
        epoch = state.epoch
        if args.tempsched:
            temperature *= dk
            say("Current temperature: ", temperature)

        # per-epoch recon grid (input | recon | argmax decode), first 8.
        # fetch_local: the batch is dp-sharded across (possibly) hosts —
        # allgather the k rows so every process feeds the jit identical
        # data (SPMD) and np.asarray never touches non-addressable
        # shards. A resume that landed exactly on the epoch boundary has
        # no batch in hand — skip the grid, keep the checkpoint.
        if state.last is not None:
            from dalle_pytorch_tpu.parallel.multihost import fetch_local
            k = min(8, args.batchSize)
            imgs = jnp.asarray(fetch_local(state.last["images"])[:k])
            recons, decoded = eval_fn(params, imgs,
                                      jax.random.fold_in(key, epoch),
                                      jnp.float32(temperature))
            grid = np.concatenate([np.asarray(imgs), np.asarray(recons),
                                   np.asarray(decoded)])
            grid_path = os.path.join(args.results_dir,
                                     f"{args.name}_epoch_{epoch}.png")
            save_image_grid(grid, grid_path, nrow=k)

        path = ckpt.save(
            ckpt.ckpt_path(args.models_dir, args.name, epoch), params,
            step=epoch, config=cfg, opt_state=opt_state, kind="vae",
            meta={"temperature": temperature, "epoch": epoch,
                  "avg_loss": avg, "global_step": state.global_step,
                  "lr_schedule": sched,
                  **({"ema_decay": args.ema_decay} if ema is not None
                     else {})}, ema=ema)
        metrics.event(event="checkpoint", path=path, epoch=epoch,
                      avg_loss=avg, temperature=temperature)
        return path

    run_supervised_loop(
        args, sup=sup, metrics=metrics, profiler=profiler, dataset=dataset,
        plan=plan, state=state, train_step=train_step,
        on_rollback=on_rollback, on_epoch_end=on_epoch_end,
        units_of=lambda images: images.shape[0], unit_name="images",
        avg_fmt=".8f")


if __name__ == "__main__":
    main()
