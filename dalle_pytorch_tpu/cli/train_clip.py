"""CLIP training CLI — contrastive text/image pretraining for the reranker.

The reference ships the CLIP model and README usage (reference
dalle_pytorch.py:161-237, README.md:90-115) but no training script; this
CLI closes that gap with the same data contract as train_dalle (captions
file + `path : caption` pairs + imagefolder, SURVEY.md §5 data contract)
so one dataset serves the whole pipeline. The trained checkpoint plugs
into ``gen_dalle --clip_name`` for generation reranking (reference
dalle_pytorch.py:354-356).

One jit train step over a ``dp`` mesh; loss is the reference's
one-directional (text→image) InfoNCE with a learned pre-exp temperature.

Run: python -m dalle_pytorch_tpu.cli.train_clip --dataPath ./imagedata
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.cli.common import (LoopState, add_common_args,
                                          load_caption_dataset, make_ema,
                                          make_optimizer, make_supervisor,
                                          plan_resume, resolve_schedule,
                                          restore_rollback,
                                          run_supervised_loop, say,
                                          setup_run, step_rng)
from dalle_pytorch_tpu.data import load_image_batch
from dalle_pytorch_tpu.models import clip as C
from dalle_pytorch_tpu.parallel import make_train_step, shard_batch
from dalle_pytorch_tpu.parallel.train import clip_loss_fn, setup_sharded


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="train CLIP (TPU-native DALLE-pytorch)")
    add_common_args(p, default_batch=32)
    p.add_argument("--dataPath", type=str, default="./imagedata")
    p.add_argument("--imageSize", type=int, default=256)
    p.add_argument("--captions_only", type=str,
                   default="od-captionsonly.txt")
    p.add_argument("--captions", type=str, default="od-captions.txt")
    p.add_argument("--load_clip", type=str, default="",
                   help="checkpoint path or name to continue training")
    p.add_argument("--grad_accum", type=int, default=1)
    # model hyperparams (reference CLIP __init__ defaults,
    # dalle_pytorch.py:162-178)
    p.add_argument("--dim_text", type=int, default=512)
    p.add_argument("--dim_image", type=int, default=512)
    p.add_argument("--dim_latent", type=int, default=512)
    p.add_argument("--num_text_tokens", type=int, default=10000)
    p.add_argument("--text_seq_len", type=int, default=256)
    p.add_argument("--text_enc_depth", type=int, default=6)
    p.add_argument("--text_heads", type=int, default=8)
    p.add_argument("--visual_enc_depth", type=int, default=6)
    p.add_argument("--visual_heads", type=int, default=8)
    p.add_argument("--visual_patch_size", type=int, default=32)
    p.add_argument("--dense", action="store_true",
                   help="dense attention (default mirrors the reference "
                        "Transformer default sparse_attn=True)")
    p.add_argument("--param_dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.set_defaults(name="clip")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    mesh, metrics, profiler = setup_run(args, unit_name="pairs")

    cfg = C.CLIPConfig(
        dim_text=args.dim_text, dim_image=args.dim_image,
        dim_latent=args.dim_latent, num_text_tokens=args.num_text_tokens,
        text_seq_len=args.text_seq_len, text_enc_depth=args.text_enc_depth,
        text_heads=args.text_heads, visual_enc_depth=args.visual_enc_depth,
        visual_heads=args.visual_heads,
        visual_image_size=args.imageSize,
        visual_patch_size=args.visual_patch_size,
        sparse_attn=not args.dense)

    # data first: the cosine schedule's default horizon is the requested
    # run length, n_epochs x steps/epoch
    vocab, dataset = load_caption_dataset(args)

    key = jax.random.PRNGKey(args.seed)

    # resolve the resume point BEFORE building the optimizer: the cosine
    # horizon must cover already-completed epochs too. --auto_resume picks
    # the newest VALID checkpoint (mid-epoch step checkpoints included).
    plan = plan_resume(args, args.name, explicit=args.load_clip,
                       steps_per_epoch=len(dataset))
    start_epoch = plan["start_epoch"] if plan else args.start_epoch
    resume_path = plan["path"] if plan else None
    sched = resolve_schedule(args, steps_per_epoch=len(dataset),
                             start_epoch=start_epoch,
                             resume_meta=plan["meta"] if plan else None)
    optimizer = make_optimizer(args, schedule=sched)
    opt_state = None
    if resume_path:
        params, opt_state, manifest = ckpt.restore_train(resume_path,
                                                         optimizer)
        cfg = C.CLIPConfig(**manifest["config"])
        say(f"resumed CLIP from {resume_path}")
        if plan["mid_epoch"]:
            metrics.resilience("resume", checkpoint=resume_path,
                               epoch=start_epoch,
                               step_in_epoch=plan["step_in_epoch"],
                               records_in_epoch=plan["skip_batches"],
                               global_step=plan["global_step"])
    else:
        params = C.clip_init(key, cfg, dtype=jnp.dtype(args.param_dtype))

    params, opt_state = setup_sharded(params, optimizer, mesh,
                                      opt_state=opt_state)
    step = make_train_step(clip_loss_fn(cfg), optimizer,
                           grad_accum=args.grad_accum)
    ema, ema_update = make_ema(args, params, resume_path or "")

    def load_batch(item):
        paths, toks = item
        images = load_image_batch(paths, args.dataPath, args.imageSize)
        return {"text": toks, "images": images,
                "mask": np.asarray(toks) != 0}          # PAD = 0

    # mutable loop state the supervisor's save_state closure reads live
    # (run_supervised_loop advances it)
    state = LoopState(epoch=start_epoch,
                      global_step=plan["global_step"] if plan else 0)

    def save_state(path):
        return ckpt.save(
            path, params, step=state.global_step, config=cfg,
            opt_state=opt_state, kind="clip",
            meta={"epoch": state.epoch, "step_in_epoch": state.epoch_i,
                  "global_step": state.global_step,
                  "records_in_epoch": state.records_in_epoch,
                  "train_loss": state.train_loss,
                  "n_batches": state.n_batches, "lr_schedule": sched,
                  **({"ema_decay": args.ema_decay} if ema is not None
                     else {})}, ema=ema)

    sup = make_supervisor(args, metrics, args.name, save_state)
    if resume_path:
        # the checkpoint we just restored from is a valid rollback
        # anchor — without it a NaN before the first cadence/epoch
        # save after resume would raise instead of rolling back
        sup.register_checkpoint(resume_path)

    def train_step(hosted, state):
        nonlocal params, opt_state, ema
        batch = shard_batch(mesh, hosted)
        batch = sup.pre_step(state.global_step, batch)
        params, opt_state, loss = step(
            params, opt_state, batch,
            step_rng(key, state.global_step))
        if ema is not None:
            ema = ema_update(ema, params)
        return loss, None

    def on_rollback(state):
        nonlocal params, opt_state, ema
        params, opt_state, ema = restore_rollback(sup, optimizer, mesh)

    def on_epoch_end(state, avg):
        epoch = state.epoch
        path = ckpt.save(
            ckpt.ckpt_path(args.models_dir, args.name, epoch), params,
            step=epoch, config=cfg, opt_state=opt_state, kind="clip",
            meta={"epoch": epoch, "avg_loss": avg,
                  "global_step": state.global_step, "lr_schedule": sched,
                  **({"ema_decay": args.ema_decay} if ema is not None
                     else {})}, ema=ema)
        metrics.event(event="checkpoint", path=path, epoch=epoch,
                      avg_loss=avg)
        return path

    run_supervised_loop(
        args, sup=sup, metrics=metrics, profiler=profiler, dataset=dataset,
        plan=plan, state=state, train_step=train_step,
        on_rollback=on_rollback, on_epoch_end=on_epoch_end,
        transform=load_batch, units_of=lambda item: args.batchSize,
        unit_name="pairs")


if __name__ == "__main__":
    main()
